"""Cross-process full-suite leg (round-4 verdict #4).

The reference CI runs its ENTIRE test suite on a 2-worker cluster
(`mpiexec -n 2`, /root/reference/.github/workflows/python-package.yml:40-46).
This runner is the rebuild's equivalent: it launches the whole pytest suite
once per rank as jax multi-controller SPMD processes — each rank owns half
of the virtual CPU devices, `jax.distributed.initialize` forms the group
(tests/conftest.py, RAMBA_TEST_PROCS branch), and every collective in every
test crosses the process boundary.

Both ranks run the identical deterministic test order (SPMD: same program
everywhere); host gathers (`ndarray.asarray`) become all-gather
collectives, and file IO writes through the driver rank with a barrier
(ramba_tpu/fileio.py).  Both ranks share one --basetemp so distributed
save/load paths agree across processes; the driver-gated writes keep a
single writer per file.

Usage:
    python scripts/two_process_suite.py [pytest args...]
    # e.g. python scripts/two_process_suite.py tests/test_fusion.py -x
    python scripts/two_process_suite.py --fault-leg

Exit 0 iff BOTH ranks' pytest runs pass.

``--fault-leg`` runs the resilience acceptance leg instead: a 2-rank SPMD
workload under ``RAMBA_FAULTS=compile:once`` — both ranks must inject the
fault in lockstep, retry the flush, produce the correct result, count
``resilience.retries`` >= 1, and stream fault/degrade events into their
per-rank RAMBA_TRACE files.

``--memory-leg`` runs the memory-governor acceptance leg: the same 2-rank
SPMD topology under a deliberately tiny ``RAMBA_HBM_BUDGET`` so pre-flush
admission control must fire on both ranks in lockstep (SPMD: the analytic
estimate is a pure function of the program, so both ranks route to the
``chunked`` rung together), produce the correct result, and stream
``memory`` events into the per-rank traces.  Host spill is intentionally
NOT exercised here: multi-controller arrays are not fully addressable, so
the governor refuses to spill them (memory.py) — the leg asserts the
admission/chunked path, which is the part that must stay rank-lockstepped.

``--perf-leg`` runs the kernel-cost-ledger acceptance leg: the same
2-rank SPMD topology under ``RAMBA_PERF=1``; both ranks run an identical
flush sequence and print the sorted kernel fingerprints from their cost
ledgers (observe/ledger.py).  The runner asserts the two sets are
IDENTICAL — the fingerprints are a pure function of program structure +
donation + semantic regime, so any rank skew here means the ranks
compiled different programs — and then runs
``scripts/trace_report.py --merge-ranks`` over the per-rank traces to
prove the cross-rank merged timeline works end to end.

``--attrib-leg`` runs the critical-path-attribution acceptance leg
(observe/attrib.py): the same 2-rank topology under ``RAMBA_PERF=1``
with a pinned ``RAMBA_PEAKS_JSON``; each rank asserts its stage sums
(plus the unattributed residual) reconcile with span wall time, then
prints its lockstep per-flush stage signatures and per-fingerprint
roofline boundedness classes.  The runner asserts both marker streams
are IDENTICAL across ranks and that ``trace_report.py --attrib`` (stage
waterfall) and ``--merge-ranks`` (per-rank stage columns, no
divergence) both build from the traces.

``--elastic-leg`` runs the elastic-lifecycle acceptance leg: a 2-rank
SPMD run (heartbeat on, watchdog armed) auto-checkpoints mid-workload
via ``elastic.CheckpointManager.maybe_save`` into a shared directory and
stops — simulating preemption after the save.  A fresh SINGLE-rank
process then ``elastic.resume``s from that directory (mesh reshape:
manifest says 2 processes, the resuming world has 1) and finishes the
workload; a straight 1-rank run of the full workload provides the
reference.  The runner asserts the two final-state sha256 digests are
BYTE-IDENTICAL — the workload is elementwise, so resharding must not
perturb a single bit.

``--serving-leg`` runs the serving-subsystem acceptance leg: each rank
drives a ``serve.Session`` through the async pipeline's staging seam in
SINGLE-THREADED deterministic order (the background worker is disabled
and dispatch is driven inline — SPMD ranks must dispatch identical
program sequences, so the fairness queue's cross-tenant coalescing
reorder is off the table here).  Four identical flushes must coalesce
into ONE fingerprint-matched batch on both ranks; the runner asserts
the coalesced fingerprint AND the full kernel-ledger key sets are
identical across ranks.

``--chaos-leg`` runs the rank-coherent-recovery acceptance leg: a
2-rank SPMD soak where EVERY fault is injected on rank 1 only
(``RAMBA_FAULTS`` ``rank=1`` payloads across the dispatch/execute/oom
sites, seeded), plus one deterministic mid-run fatal burst that drives
a coherent quarantine.  Phase ON (``RAMBA_COHERENCE=on``) asserts the
consensus control plane absorbs the skew: byte-identical per-iteration
results on both ranks, identical coherence decision sequences (same
sites, same epochs, same decisions), identical rung-transition and
retry sequences, equal quarantine counts (each stamped with its
agreement epoch), zero watchdog ``stall`` events, and zero
local-fallback rounds.  Phase OFF re-runs the same seed with
``RAMBA_COHERENCE=off`` and asserts the historical failure mode comes
back: rank-local recovery diverges the rungs, the ranks' host gathers
mispair, and the run ends in differing results / a wedged rank
(deadline-killed) — demonstrating the protocol is what fixes it.

``--reshard-leg`` runs the resharding/elasticity acceptance leg.
Phase 1 (2-rank SPMD): a row-sharded array reshards to column-sharded
then to replicated through the staged device-collective schedule
(coherence plan fence + per-stage gates), asserted byte-identical on
both ranks and within the ledger-verified peak-live bound; then a
rank-skewed mid-reshard fault (``reshard:stage:after=2:rank=1``) must
abort the epoch on BOTH ranks (the stage gate turns rank 1's local
fault into a fleet-wide rollback before any collective mispairs),
after which a clean retry ends byte-identical with zero watchdog
stalls.  Phase 2 (single-rank): the same workload reshapes a 2-device
mesh down to 1 device via ``elastic.live_reshape`` twice — once on the
live rung, once with an injected ``reshard:plan`` fault forcing the
drain→checkpoint→resume fallback — and the two digests must match.

``--telemetry-leg`` runs the live-telemetry acceptance leg: both ranks
serve a traced ``serve.Session`` flush (one FIXED trace_id shared across
ranks — the cross-rank causal chain), start the Prometheus exporter on
an ephemeral port, and scrape their own ``/metrics``.  The runner
asserts each rank's scrape is labeled with its own distinct
``rank="<r>"`` and that the shared trace_id landed in BOTH ranks'
RAMBA_TRACE event files — the inputs ``trace_report.py --trace`` needs
to reconstruct one request across the fleet.

``--fleet-leg`` runs the fleet-observability-federation acceptance leg
(PR 16): three INDEPENDENT replica processes (not SPMD ranks) run the
identical traced serving flush with ``RAMBA_FLEET_DIR`` pointed at one
shared snapshot spool.  The runner drives ``scripts/fleet_collector.py``
through the whole replica lifecycle: all replicas healthy with lockstep
kernel fingerprints, the fleet goodput rollup reconciling against the
raw per-replica spool documents within 1%, an injected torn document
classified stale without a collector crash, a replica SIGKILLed
mid-soak flagged dead within 2x the publish interval, and the
cross-process ``trace_report.py --trace`` chain stitched over the
per-replica trace directories.

``--router-leg`` runs the fleet serving-plane acceptance leg (PR 17):
a router process (its own RAMBA_TRACE stream) drives replica servers
spawned via ``scripts/fleet_router.py`` against one snapshot spool and
one shared artifact tier.  Phase 1 warms the tier from a cold replica
(demand compiles + ``persist.save_topk``) and pins the no-fault
reference digest; phase 2 proves a second cold replica comes up warm
off the shared AOT tier (cross-writer persist hits, byte-identical
digests, shared memo lane off); phase 3 proves the shared memo lane
(cross-replica memo hits, near-zero demand compiles); phase 4 SIGKILLs
the replica serving a tenant mid-soak and asserts the router trips its
fleet breaker, redirects, heals the tenant by deterministic replay on
the survivor, and every tenant's digest stays byte-identical.  The
stitched ``trace_report.py --merge-ranks`` / ``--trace`` views over the
router + replica trace files must show the redirect/heal chain.

``--integrity-leg`` runs the data-integrity acceptance leg (two
phases).  ON: a 2-rank SPMD run with shadow audits armed
(``RAMBA_AUDIT=1``) and a seeded one-shot flip of rank 1's shadow bytes
(``audit:shadow:flip``) — both ranks must agree the audit verdict via
the coherence round (rank 0 saw no local mismatch yet records the
agreed one), suppress the memo insert coherently, serve the correct
primary result, and emit ``integrity`` trace events.  OFF: a
single-process reproduction of the exact wrong-answer serve the plane
prevents — a shared memo blob clobbered with a *valid but wrong*
unstamped payload is served verbatim under ``RAMBA_INTEGRITY=0``, then
caught (evict + recompute, correct answer) with the plane on.

``--memo-leg`` runs the result-memoization acceptance leg: both ranks
under ``RAMBA_MEMO=1`` canonicalize the same program (including its
commutative-operand swap — ``analyze.canonicalize`` must produce the
SAME chash for ``(a+b)*2`` and ``(b+a)*2`` on both ranks) and then
flush it repeatedly over stable buffers.  Memo hits are rank-local
decisions that SKIP dispatch, so the cache MUST hit in lockstep: a
rank that replays from cache while its peer executes would mispair the
post-flush gathers.  The runner asserts both ranks print the identical
canonical hash, the identical hit/insert counts, the correct value,
and that each per-rank trace carries memo-served flush spans
(``cache == "memo"``).

``--plancache-leg`` runs the plan-certificate acceptance leg: both
ranks under ``RAMBA_PLANCERT=1 RAMBA_VERIFY=strict`` flush the same
program repeatedly.  The cache key and invalidation signature are pure
functions of rank-identical state (program structure, avals, mesh
epoch, rule set), so hit/miss decisions MUST be lockstep — a rank
redeeming a certificate while its peer re-analyzes would skew the
flush sequences.  The leg runs the epoch-batched ``agree()`` round at
a small batch size, asserts zero divergences, and the runner compares
hit/store/stale markers across ranks and asserts each per-rank trace
carries certificate-redeemed flush spans (``plan_cache == "hit"``).

``--warmstart-leg`` runs the compile-class / warm-start acceptance leg
(PR 14): two phases of two ranks each, sharing per-rank ``RAMBA_CACHE``
directories across phases.  Under ``RAMBA_COMPILE_CLASSES=pow2`` the
bucket decision is a pure function of (program, shapes, policy), so
both SPMD ranks must pick the IDENTICAL compile class per fingerprint
— skewed classes would compile different executables and desync the
collective schedule.  The cold phase populates each rank's persistent
cache (``persist.save_topk``); the warm phase replays the same shapes
and must hit the AOT lane in LOCKSTEP (equal, nonzero persist-hit
counts on both ranks).  The runner compares the per-rank class-decision
tables within and across phases and the persist hit counts across
ranks.

``--sampling-leg`` runs the self-metering-observability acceptance leg
(PR 20): two ranks under ``RAMBA_ATTRIB=sample:4`` +
``RAMBA_TRACE_SAMPLE=4`` with a rank-skewed ``execute:delay`` fault.
The fence verdict is the fingerprint's flush sequence number (never
RNG, never timing), so both ranks must fence the IDENTICAL sequence
numbers per fingerprint and classify every roofline identically even
while rank 1 runs 40 ms slower per execute — a timing-derived sampler
would skew here and desync the collective schedule.  Steady-state
sessions use deterministic trace ids whose sha256 verdict keeps
exactly 5 of 48 chains in the file lane (>= 4x volume drop by
construction); one seeded slow flush on a sampled-OUT trace must trip
the sentinel on both ranks and the tail latch must retroactively
replay that trace's full buffered chain into the file.  The runner
compares fence/roofline markers across ranks, asserts zero stalls and
zero local-fallback rounds, and greps each rank's trace file for the
latched chain and the steady-state volume ratio.
"""

from __future__ import annotations

import os
import shutil
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# SPMD workload for the fault leg: each rank forms the process group
# itself (no pytest/conftest in the loop), runs a fused chain that must
# survive one injected compile fault per rank, and checks its own retry
# counters.  argv: <rank> <coordinator>.
_FAULT_WORKLOAD = """
import sys
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
a = rt.arange(4096) * 2.0 + 1.0
s = float(rt.sum(a))
exp = float(np.sum(np.arange(4096) * 2.0 + 1.0))
assert abs(s - exp) <= 1e-5 * abs(exp), (s, exp)
from ramba_tpu import diagnostics
c = diagnostics.counters()
assert c.get('resilience.retries', 0) >= 1, c
print('FAULT_LEG_OK rank=%d retries=%d' % (rank, c['resilience.retries']))
"""


# SPMD workload for the memory leg: each rank forms the process group,
# runs a multi-op chain whose analytic peak estimate exceeds the tiny
# injected HBM budget, and checks that admission control rerouted the
# flush to the chunked rung while still producing the right answer.
# argv: <rank> <coordinator>.
_MEMORY_WORKLOAD = """
import sys
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
a = rt.arange(65536) * 2.0 + 1.0
b = rt.sqrt(a) + a * 0.5
s = float(rt.sum(b))
an = np.arange(65536) * 2.0 + 1.0
exp = float(np.sum(np.sqrt(an) + an * 0.5))
assert abs(s - exp) <= 1e-3 * abs(exp), (s, exp)
from ramba_tpu import diagnostics
c = diagnostics.counters()
ok = (c.get('memory.admission_rejects', 0) >= 1
      or c.get('memory.evictions', 0) >= 1)
assert ok, c
chunked = [f for f in diagnostics.last_flushes(20)
           if f.get('admission') == 'chunked'
           or f.get('degraded') == 'chunked']
assert chunked, diagnostics.last_flushes(20)
print('MEMORY_LEG_OK rank=%d rejects=%d' % (
    rank, c.get('memory.admission_rejects', 0)))
"""


# SPMD workload for the perf leg: each rank forms the process group, runs
# the same flush sequence twice (so every kernel has both a miss and a
# hit), and prints its ledger's sorted kernel fingerprints for the runner
# to compare across ranks.  argv: <rank> <coordinator>.
_PERF_WORKLOAD = """
import sys
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
for _ in range(3):
    a = rt.arange(8192) * 2.0 + 1.0
    s = float(rt.sum(a))
    b = rt.sqrt(rt.arange(4096) + 1.0)
    s2 = float(rt.sum(b))
exp = float(np.sum(np.arange(8192) * 2.0 + 1.0))
assert abs(s - exp) <= 1e-5 * abs(exp), (s, exp)
from ramba_tpu import diagnostics
rep = diagnostics.perf_report()
keys = sorted(rep['kernels'])
assert keys, rep
execs = sum(k['exec']['count'] for k in rep['kernels'].values())
assert execs >= 1, rep
print('PERF_LEG_KEYS rank=%d %s' % (rank, ','.join(keys)))
"""


# SPMD workload for the attribution leg: each rank runs the same flush
# sequence, then prints (a) the per-flush stage signatures in lockstep
# order and (b) the per-fingerprint roofline boundedness classes.  Both
# must be identical across ranks: stage stamping is deterministic control
# flow and classification is pure math over rank-agreed cost models and
# a pinned peak table.  Each rank also checks that its stage sums plus
# the unattributed residual reconcile with span wall time.
# argv: <rank> <coordinator>.
_ATTRIB_WORKLOAD = """
import sys
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu import diagnostics
from ramba_tpu.observe import attrib
for _ in range(3):
    a = rt.arange(8192) * 2.0 + 1.0
    s = float(rt.sum(a))
    b = rt.sqrt(rt.arange(4096) + 1.0)
    s2 = float(rt.sum(b))
exp = float(np.sum(np.arange(8192) * 2.0 + 1.0))
assert abs(s - exp) <= 1e-5 * abs(exp), (s, exp)
sigs = []
for f in diagnostics.last_flushes(50):
    st = f.get('stages')
    if st is None:
        continue
    order = [k for k in attrib.STAGES if k in st]
    wall = f.get('wall_s') or 0.0
    tot = sum(st.values()) + f.get('unattributed_s', 0.0)
    assert abs(tot - wall) <= max(0.05 * wall, 1e-3), (wall, tot, st)
    sigs.append(f.get('label', '?') + ':' + ','.join(order))
assert sigs, diagnostics.last_flushes(5)
rep = diagnostics.perf_report()
roofs = (rep.get('attribution') or {}).get('rooflines') or {}
assert roofs, rep.get('attribution')
roofmark = ','.join('%s=%s' % (fp, roofs[fp]['bound'])
                    for fp in sorted(roofs))
print('ATTRIB_LEG_STAGES rank=%d %s' % (rank, ';'.join(sigs)))
print('ATTRIB_LEG_ROOFS rank=%d %s' % (rank, roofmark))
"""


# SPMD workload for the sampling leg: 48 steady-state serving sessions
# with deterministic trace ids under RAMBA_ATTRIB=sample:4 +
# RAMBA_TRACE_SAMPLE=4, then one seeded slow flush on a sampled-OUT
# trace.  The fence decisions (per-fingerprint flush sequence numbers)
# and roofline bounds are printed for the runner to compare across
# ranks; the rank-skewed env fault makes rank 1 slower per execute, so
# any timing dependence in the sampler would diverge the markers.
# argv: <rank> <coordinator>.
_SAMPLING_WORKLOAD = """
import sys
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu import diagnostics, serve
from ramba_tpu.observe import attrib, events, registry
from ramba_tpu.resilience import faults
assert attrib.fence_enabled() and attrib.sample_every() == 4
assert events.trace_sample_every() == 4
# steady state: one-flush sessions with deterministic trace ids; the
# sha256 head-sampling verdict keeps exactly 5 of these 48 chains
tids = ['steady-%03d' % i for i in range(48)]
kept = [t for t in tids if events.trace_sampled_in(t)]
assert len(kept) == 5, kept
x = None
for tid in tids:
    with serve.Session(trace_id=tid) as s:
        a = rt.arange(2048) * 2.0 + 1.0
        x = float(np.asarray(a).sum())
exp = float((np.arange(2048) * 2.0 + 1.0).sum())
assert abs(x - exp) <= 1e-5 * abs(exp), (x, exp)
# seeded slow flush on a sampled-OUT trace: warm the program's rolling
# p50, then delay one execute on BOTH ranks (faults.active suspends the
# rank-skew env plan) -> the sentinel fires and the tail latch must
# replay the whole buffered chain into the file lane
assert not events.trace_sampled_in('slow-0')
with serve.Session(trace_id='slow-0') as s:
    for _ in range(6):
        b = rt.sqrt(rt.arange(4099) + 1.0)
        float(np.asarray(b).sum())
    # 1500 ms: the SPMD gather collective drags rank 1's 40 ms skew into
    # every flush's wall (~55 ms p50), so the seed must clear 8x THAT
    with faults.active('execute:delay:ms=1500'):
        b = rt.sqrt(rt.arange(4099) + 1.0)
        float(np.asarray(b).sum())
rt.sync()
slow = events.last(0, type='slow_flush')
assert slow, 'seeded slow flush never tripped the sentinel'
assert slow[-1].get('trace_id') == 'slow-0', slow[-1]
ring = events.snapshot_ring()
stalls = sum(1 for e in ring if e.get('type') == 'stall')
local = sum(1 for e in ring if e.get('type') == 'coherence'
            and e.get('outcome') == 'local')
est = sum(1 for e in ring if e.get('type') == 'flush'
          and e.get('device_source') == 'estimated')
fen = sum(1 for e in ring if e.get('type') == 'flush'
          and e.get('device_source') == 'fenced')
rep = diagnostics.perf_report()
roofs = (rep.get('attribution') or {}).get('rooflines') or {}
assert roofs, rep.get('attribution')
samp = attrib.sampling_report()
fences = ';'.join(
    '%s:%s/%d' % (fp, ','.join(str(q) for q in d['fenced_seqs']),
                  d['calls'])
    for fp, d in sorted(samp['fingerprints'].items()))
roofmark = ','.join('%s=%s' % (fp, roofs[fp]['bound'])
                    for fp in sorted(roofs))
print('SAMPLING_LEG_FENCES rank=%d %s' % (rank, fences))
print('SAMPLING_LEG_ROOFS rank=%d %s' % (rank, roofmark))
print('SAMPLING_LEG_HEALTH rank=%d stalls=%d local=%d est=%d fenced=%d '
      'latched=%d' % (rank, stalls, local, est, fen,
                      registry.get('events.tail_latched')))
"""


# SPMD workload for the memo leg: each rank forms the process group,
# canonicalizes the shared program (asserting the commutative swap
# collapses to the same chash locally), then flushes it four times over
# stable buffers under RAMBA_MEMO=1 — one insert, three hits.  The
# canonical hash and the hit/insert counters are printed for the runner
# to compare across ranks: the hash is a pure function of program
# structure and the cache decision is deterministic given it, so any
# skew here means the ranks would dispatch different flush sequences.
# argv: <rank> <coordinator>.
_MEMO_WORKLOAD = """
import sys
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu import analyze
from ramba_tpu.core import fuser, memo
assert memo.enabled(), 'RAMBA_MEMO not armed'
a = rt.arange(4096) / 100.0
b = rt.arange(4096) * 0.5 + 1.0
rt.sync()
vals = [float(rt.sum((a + b) * 2.0)) for _ in range(4)]
assert max(vals) == min(vals), vals
p1, _l1, _ = fuser._prepare_program([((a + b) * 2.0)._expr])
p2, _l2, _ = fuser._prepare_program([((b + a) * 2.0)._expr])
c1, c2 = analyze.canonicalize(p1), analyze.canonicalize(p2)
assert c1.chash == c2.chash, (c1.chash, c2.chash)
an = np.arange(4096)
exp = float(np.sum((an / 100.0 + (an * 0.5 + 1.0)) * 2.0))
assert abs(vals[0] - exp) <= 1e-4 * abs(exp), (vals[0], exp)
snap = memo.cache.snapshot()
assert snap['hits'] >= 3, snap
print('MEMO_LEG rank=%d chash=%s hits=%d inserts=%d' % (
    rank, c1.chash, snap['hits'], snap['inserts']))
"""


# SPMD workload for the plancache leg: each rank forms the process
# group, flushes the same fused chain five times under strict verify
# with the plan cache armed, then drains the batched coherence round.
# The cache decision sequence (1 store + 4 hits) is a deterministic
# function of rank-identical inputs, so the printed counters must match
# across ranks, and the agree() exchange must see equal batch counts
# (zero divergences).  argv: <rank> <coordinator>.
_PLANCACHE_WORKLOAD = """
import sys
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu.core import fuser, plancache
assert plancache.enabled(), 'RAMBA_PLANCERT not armed'
a = rt.arange(4096) / 100.0
b = rt.arange(4096) * 0.5 + 1.0
rt.sync()
vals = [float(rt.sum((a + b) * 2.0)) for _ in range(5)]
assert max(vals) == min(vals), vals
an = np.arange(4096)
exp = float(np.sum((an / 100.0 + (an * 0.5 + 1.0)) * 2.0))
assert abs(vals[0] - exp) <= 1e-4 * abs(exp), (vals[0], exp)
plancache.flush_agree()
snap = plancache.snapshot()
assert snap.get('hits', 0) >= 3, snap
assert not snap.get('divergences'), snap
assert not snap.get('stale'), snap
print('PLANCACHE_LEG rank=%d hits=%d stores=%d stale=%d agree=%d '
      'div=%d' % (rank, snap.get('hits', 0), snap.get('stores', 0),
                  snap.get('stale', 0), snap.get('agree_rounds', 0),
                  snap.get('divergences', 0)))
"""


# SPMD workload for the autotune leg: each rank forms the process group
# and drives the same fused chain under RAMBA_AUTOTUNE=race until the
# backend race latches (or the iteration budget runs out), then prints
# its decision table.  Selection is ledger-count-driven, and counts
# advance in lockstep under SPMD, so both ranks must latch the SAME
# backend per fingerprint — the runner compares the tables.
# argv: <rank> <coordinator>.
_AUTOTUNE_WORKLOAD = """
import os
import sys
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu.core import autotune
assert autotune.mode() == 'race', autotune.mode()
n = 128 * 256
base = rt.arange(n) / 1000.0
rt.sync()
vals = []
for _ in range(20):
    B = rt.sin(base)
    C = rt.cos(base)
    D = B * B + C * C
    del B, C
    vals.append(float(rt.sum(D)))
    del D
    if autotune.latched_via_autotune():
        break
assert max(vals) == min(vals), vals
rep = autotune.report()
dec = {fp: d['backend'] for fp, d in rep['decisions'].items()}
assert dec, rep
cache = os.environ.get('RAMBA_AUTOTUNE_CACHE')
if cache:
    import json
    with open(cache) as f:
        table = json.load(f)
    for fp, b in dec.items():
        assert table['decisions'][fp]['backend'] == b, (fp, table)
print('AUTOTUNE_LEG_DECISIONS rank=%d %s'
      % (rank, ','.join('%s=%s' % kv for kv in sorted(dec.items()))))
"""


# SPMD workload for the warmstart leg: each rank forms the process
# group, arms the persistent cache on its own RAMBA_CACHE dir, and
# drives the same elementwise chain across four leading extents under
# RAMBA_COMPILE_CLASSES=pow2 (small enough to stay replicated, so the
# eager pad/slice wrapper touches only fully-addressable buffers).  The
# cold phase additionally serializes AOT executables; the warm phase
# must hit them.  Markers carry the per-fingerprint class-decision
# table, the persist hit count, and the compile totals for the runner
# to compare across ranks and phases.  argv: <rank> <coordinator>
# <phase: cold|warm>.
_WARMSTART_WORKLOAD = """
import sys
import numpy as np
rank, coord, phase = int(sys.argv[1]), sys.argv[2], sys.argv[3]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu import common
from ramba_tpu.compile import classes, persist
from ramba_tpu.observe import ledger
assert classes.enabled(), 'RAMBA_COMPILE_CLASSES not armed'
common.setup_persistent_cache()
persist.reconfigure()
assert persist.armed(), persist.snapshot()
for n in (3, 5, 9, 12):
    x = rt.array(np.arange(n * 8, dtype=np.float32).reshape(n, 8))
    y = x * 2.0 + 1.0
    rt.sync()
    got = float(rt.sum(y))
    exp = float(np.sum(np.arange(n * 8, dtype=np.float32)
                       .reshape(n, 8) * 2.0 + 1.0))
    assert abs(got - exp) <= 1e-4 * abs(exp), (n, got, exp)
snap = classes.snapshot()
assert snap['planned'] >= 4, snap
dec = {fp: tok for fp, tok in classes.decisions().items()
       if tok is not None}
assert dec, classes.decisions()
if phase == 'cold':
    rep = persist.save_topk(8)
    assert rep['stored'] + rep['skipped'] >= 1, rep
p = persist.snapshot()
if phase == 'warm':
    assert p['hits'] >= 1, p
ks = ledger.snapshot()['kernels'].values()
compiles = sum(k['compiles'] for k in ks)
compile_s = sum(k['compile_s'] for k in ks)
table = ','.join('%s=%s:%s' % (fp, tok[0], tok[1])
                 for fp, tok in sorted(dec.items()))
print('WARMSTART_LEG rank=%d phase=%s classes=%s persist_hits=%d '
      'compiles=%d compile_s=%.4f'
      % (rank, phase, table, p['hits'], compiles, compile_s))
"""


# SPMD workload for the serving leg: each rank opens one serving session
# and pushes four structurally-identical flushes plus one distinct one
# through the async pipeline's enqueue/dispatch seam, driving dispatch
# inline (worker disabled) so both ranks execute the identical program
# sequence.  argv: <rank> <coordinator>.
_SERVING_WORKLOAD = """
import sys
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu import diagnostics, serve
from ramba_tpu.serve.pipeline import CompilePipeline
pipe = CompilePipeline(coalesce=8)
pipe._ensure_worker = lambda: None  # deterministic: dispatch inline below
with serve.Session(tenant='spmd', pipeline=pipe) as s:
    arrs, tickets = [], []
    for i in range(4):
        arrs.append(rt.arange(8192) * 2.0 + 1.0)
        tickets.append(s.flush())
    group = pipe.queue.pop_group(
        8, fingerprint_of=lambda t: t.work.fingerprint, timeout=0)
    assert len(group) == 4, len(group)
    fp = group[0].work.fingerprint
    pipe._dispatch_group(group)
    for t in tickets:
        assert t.wait(timeout=120) == [] and t.coalesced == 4
    exp = np.arange(8192) * 2.0 + 1.0
    for a in arrs:
        got = np.asarray(a)
        assert np.allclose(got, exp), got[:4]
    b = rt.sqrt(rt.arange(4096) + 1.0)
    t2 = s.flush()
    g2 = pipe.queue.pop_group(
        8, fingerprint_of=lambda t: t.work.fingerprint, timeout=0)
    assert len(g2) == 1, len(g2)
    pipe._dispatch_group(g2)
    t2.wait(timeout=120)
    assert np.allclose(np.asarray(b), np.sqrt(np.arange(4096) + 1.0))
pipe.stop()
rep = serve.tenant_report()
assert rep['spmd']['flushes'] >= 5, rep
assert rep['spmd']['quota_rejects'] == 0, rep
from ramba_tpu.observe import ledger
keys = ledger.kernel_keys()
assert keys, 'empty kernel ledger'
print('SERVING_LEG_COALESCE rank=%d fp=%s' % (rank, fp))
print('SERVING_LEG_KEYS rank=%d %s' % (rank, ','.join(sorted(keys))))
"""


# SPMD workload for the overload leg: a rank-skewed ``serve:admit`` fault
# makes rank 1 PROPOSE shedding the first three flushes; under engaged
# coherence the ``serve:shed`` agreement round must shed them on BOTH
# ranks (identical verdict, same epoch) so the fleet never splits into
# "rank 0 executed a collective rank 1 skipped".  With RAMBA_COHERENCE=off
# the same seed must reproduce the divergence.  argv: <rank> <coordinator>.
_OVERLOAD_WORKLOAD = """
import os, sys, time
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu import serve
from ramba_tpu.serve import overload
from ramba_tpu.serve.pipeline import CompilePipeline
coh = os.environ.get('RAMBA_COHERENCE', 'auto')
pipe = CompilePipeline()
pipe._ensure_worker = lambda: None  # lockstep: dispatch inline below
arrs = []
with serve.Session(tenant='ov', pipeline=pipe) as s:
    for i in range(8):
        a = rt.arange(4096) * float(i + 1) + 0.5
        arrs.append(a)
        t = s.flush()
        group = pipe.queue.pop_group(1, timeout=5)
        assert len(group) == 1, (i, len(group))
        t0 = time.perf_counter()
        pipe._dispatch_group(group)
        try:
            t.wait(timeout=120)
            print('OVERLOAD_RESULT idx=%d verdict=OK' % i, flush=True)
        except overload.ShedError as e:
            wall_ms = (time.perf_counter() - t0) * 1e3
            assert e.shed_classification == 'shed', e
            assert wall_ms < 2000.0, wall_ms  # shed, not executed-then-failed
            print('OVERLOAD_RESULT idx=%d verdict=SHED reason=%s epoch=%s'
                  % (i, e.reason, e.epoch), flush=True)
    if coh == 'on':
        # both ranks shed the identical set, so the self-heal flushes
        # below are the identical collective sequence on every rank
        for i, a in enumerate(arrs):
            got = float(np.asarray(a).sum())
            exp = float((np.arange(4096) * float(i + 1) + 0.5).sum())
            tag = 'OK' if abs(got - exp) <= 1e-3 * max(1.0, abs(exp)) else 'BAD'
            print('OVERLOAD_HEAL idx=%d %s' % (i, tag), flush=True)
    s.close(drain=False)
pipe.stop()
from ramba_tpu.observe import registry
print('OVERLOAD_COUNTS shed=%d fault=%d' % (
    registry.get('serve.shed'), registry.get('serve.shed.fault')),
    flush=True)
"""


# SPMD workload for the telemetry leg: each rank opens a serving session
# that JOINS one fixed trace_id (the same request fanned out across the
# fleet), drives a traced flush through the pipeline seam inline, then
# starts the metrics exporter on an ephemeral port and scrapes itself.
# argv: <rank> <coordinator> <trace_id>.
_TELEMETRY_WORKLOAD = """
import sys
import urllib.request
import numpy as np
rank, coord, trace = int(sys.argv[1]), sys.argv[2], sys.argv[3]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu import serve
from ramba_tpu.observe import telemetry
from ramba_tpu.serve.pipeline import CompilePipeline
pipe = CompilePipeline(coalesce=8)
pipe._ensure_worker = lambda: None  # deterministic: dispatch inline
with serve.Session(tenant='spmd', pipeline=pipe, trace_id=trace) as s:
    assert s.trace_id == trace
    a = rt.arange(8192) * 2.0 + 1.0
    t = s.flush()
    g = pipe.queue.pop_group(
        8, fingerprint_of=lambda t: t.work.fingerprint, timeout=0)
    assert len(g) == 1, len(g)
    pipe._dispatch_group(g)
    assert t.wait(timeout=120) == []
    assert t.trace_id == trace, t.trace_id
    assert np.allclose(np.asarray(a), np.arange(8192) * 2.0 + 1.0)
pipe.stop()
port = telemetry.start(port=0)
body = urllib.request.urlopen(
    'http://127.0.0.1:%d/metrics' % port, timeout=30).read().decode()
telemetry.stop()
labels = sorted({ln.split('rank=\"')[1].split('\"')[0]
                 for ln in body.splitlines() if 'rank=\"' in ln})
assert 'ramba_serve_tenant_flushes_total' in body, body[:400]
assert 'ramba_flush_e2e_seconds_bucket' in body, body[:400]
print('TELEMETRY_LEG_SCRAPE rank=%d labels=%s port=%d' % (
    rank, ','.join(labels), port))
"""


# Workload for the fleet leg: N INDEPENDENT replica processes (not SPMD
# ranks — each is its own single-process serving job, the fleet topology
# the snapshot spool federates).  Each replica runs the IDENTICAL traced
# serving flush (lockstep kernel fingerprints across the fleet), lets
# the spool publisher autostart off the flush path, forces one
# synchronous publish so the READY marker implies a document on disk,
# then soaks (publishing every RAMBA_FLEET_INTERVAL_S) until killed or
# the soak budget ends.  argv: <idx> <trace_id> <soak_s>.
_FLEET_WORKLOAD = """
import sys
import time
import numpy as np
idx, trace, soak_s = int(sys.argv[1]), sys.argv[2], float(sys.argv[3])
import ramba_tpu as rt
from ramba_tpu import serve
from ramba_tpu.observe import fleet, ledger
from ramba_tpu.serve.pipeline import CompilePipeline
pipe = CompilePipeline(coalesce=8)
pipe._ensure_worker = lambda: None  # deterministic: dispatch inline
with serve.Session(tenant='fleet', pipeline=pipe, trace_id=trace) as s:
    assert s.trace_id == trace
    a = rt.arange(4096) * 3.0 + 1.0  # IDENTICAL program on every replica
    t = s.flush()
    g = pipe.queue.pop_group(
        8, fingerprint_of=lambda t: t.work.fingerprint, timeout=0)
    assert len(g) == 1, len(g)
    pipe._dispatch_group(g)
    assert t.wait(timeout=120) == []
    assert np.allclose(np.asarray(a), np.arange(4096) * 3.0 + 1.0)
pipe.stop()
assert fleet.started(), 'spool publisher must autostart off the flush path'
path = fleet.publish()
assert path, path
print('FLEET_REPLICA_OK idx=%d fps=%s' % (
    idx, ','.join(ledger.kernel_keys())), flush=True)
deadline = time.monotonic() + soak_s
while time.monotonic() < deadline:
    time.sleep(0.05)
print('FLEET_SOAK_DONE idx=%d' % idx, flush=True)
"""


# SPMD workload for the elastic leg, phase 1: two ranks run the first
# half of a deterministic elementwise workload with heartbeat + watchdog
# on, auto-checkpoint at the cadence step into a SHARED root, and stop —
# a preemption right after the save.  argv: <rank> <coordinator> <root>.
_ELASTIC_SPMD_WORKLOAD = """
import sys
import numpy as np
rank, coord, root = int(sys.argv[1]), sys.argv[2], sys.argv[3]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu.resilience import elastic
elastic.start_heartbeat(0.2)
box = {}
mgr = elastic.CheckpointManager(root, keep=2, every_steps=2)
mgr.register('state', lambda: {'x': box['x']})
box['x'] = rt.arange(8192) * 1.0
for step in (1, 2, 3):
    box['x'] = box['x'] * 1.000001 + float(step)
    if mgr.maybe_save(step):
        print('ELASTIC_LEG_SAVED rank=%d step=%d' % (rank, step))
assert mgr.latest() == 2, mgr.all_steps()
elastic.stop_heartbeat()
print('ELASTIC_LEG_PHASE1_OK rank=%d beats=%d' % (
    rank, elastic.report()['heartbeats']))
"""


# Elastic leg, phase 2: a fresh SINGLE-rank world resumes from the
# 2-rank checkpoint (mesh reshape 2->1) and finishes the workload.
# argv: <root>.
_ELASTIC_RESUME_WORKLOAD = """
import sys
import hashlib
import numpy as np
root = sys.argv[1]
import jax
assert jax.process_count() == 1, jax.process_count()
import ramba_tpu as rt
from ramba_tpu.resilience import elastic
res = elastic.resume(root)
assert res.manifest['process_count'] == 2, res.manifest
assert res.step == 2, res.step
x = rt.asarray(np.asarray(res.state['state']['x']))
for step in (3, 4, 5, 6):
    x = x * 1.000001 + float(step)
digest = hashlib.sha256(np.ascontiguousarray(np.asarray(x))
                        .tobytes()).hexdigest()
print('ELASTIC_LEG_DIGEST %s' % digest)
"""


# Elastic leg, reference: the same workload end to end in one 1-rank
# process, no checkpoint in the loop.  argv: none.
_ELASTIC_REF_WORKLOAD = """
import hashlib
import numpy as np
import ramba_tpu as rt
x = rt.arange(8192) * 1.0
for step in (1, 2, 3, 4, 5, 6):
    x = x * 1.000001 + float(step)
digest = hashlib.sha256(np.ascontiguousarray(np.asarray(x))
                        .tobytes()).hexdigest()
print('ELASTIC_LEG_REF %s' % digest)
"""


# SPMD workload for the reshard leg, phase 1: row → column → replicated
# through the staged schedule, ledger-bound check, then a rank-skewed
# mid-reshard fault that must roll back coherently on BOTH ranks.
# argv: <rank> <coordinator>.
_RESHARD_SPMD_WORKLOAD = """
import sys
import hashlib
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu.observe import registry
from ramba_tpu.parallel import mesh as mesh_mod
from ramba_tpu.parallel import reshard as reshard_mod
from ramba_tpu.resilience import elastic, faults, memory
ax = tuple(mesh_mod.get_mesh().axis_names)
data = np.arange(512 * 64, dtype=np.float32).reshape(512, 64)
ref = hashlib.sha256(np.ascontiguousarray(data).tobytes()).hexdigest()
a = rt.asarray(data)
rt.sync()
cap = 1 << 13
plan = reshard_mod.plan_reshard(a.shape, a.dtype, (ax,), (None,) + (ax,),
                                max_stage_bytes=cap)
assert len(plan.stages) > 1, plan.describe()
live0 = memory.ledger.live_bytes + memory.ledger.transient_bytes
peak0 = memory.ledger.peak_live_bytes
rt.reshard(a, (None,) + (ax,), max_stage_bytes=cap)   # row -> column
peak1 = memory.ledger.peak_live_bytes
bound = (live0 - plan.total_bytes) + plan.peak_bound_bytes
assert peak1 <= max(peak0, bound), (peak1, peak0, bound)
rt.reshard(a, ())                                     # column -> replicated
got = hashlib.sha256(np.ascontiguousarray(a.asarray())
                     .tobytes()).hexdigest()
assert got == ref, (got, ref)
assert memory.ledger.transient_bytes == 0
print('RESHARD_LEG_DIGEST rank=%d %s' % (rank, got), flush=True)
print('RESHARD_LEG_PEAK rank=%d peak=%d bound=%d' % (rank, peak1, bound),
      flush=True)
# rank-skewed mid-reshard fault: rank 1 faults at stage 2; the stage
# gate must turn that into a fleet-wide rollback on the SAME stage.
rt.reshard(a, (ax,), max_stage_bytes=cap)             # back to row
faults.configure('reshard:stage:after=2:rank=1')
try:
    rt.reshard(a, (None,) + (ax,), max_stage_bytes=cap)
    raise SystemExit('expected ReshardError on rank %d' % rank)
except reshard_mod.ReshardError:
    pass
faults.configure(None)
assert registry.get('reshard.rollbacks') >= 1
rt.reshard(a, (None,) + (ax,), max_stage_bytes=cap)   # clean retry
rt.reshard(a, ())
got2 = hashlib.sha256(np.ascontiguousarray(a.asarray())
                      .tobytes()).hexdigest()
assert got2 == ref, (got2, ref)
stalls = elastic.report()['stalls']
assert stalls == 0, stalls
print('RESHARD_LEG_FAULT rank=%d digest=%s rollbacks=%d stalls=%d' % (
    rank, got2, registry.get('reshard.rollbacks'), stalls), flush=True)
"""


# Reshard leg, phase 2: single rank, 2-device mesh reshaped down to 1
# device in place.  argv: <mode> — 'live' runs the top rung, 'checkpoint'
# injects a reshard:plan fault so the drain->checkpoint->resume fallback
# must carry the reshape; both print the same-workload digest.
_RESHARD_LIVE_WORKLOAD = """
import sys
import hashlib
import time
import numpy as np
mode = sys.argv[1]
import jax
assert jax.process_count() == 1, jax.process_count()
import ramba_tpu as rt
from ramba_tpu.parallel import mesh as mesh_mod
from ramba_tpu.resilience import elastic, faults
mesh_mod.set_mesh(jax.sharding.Mesh(np.asarray(jax.devices()[:2]), ('d0',)))
x = rt.arange(8192) * 1.0
for step in (1, 2, 3):
    x = x * 1.000001 + float(step)
np.asarray(x)  # materialise on the 2-device mesh
if mode == 'checkpoint':
    faults.configure('reshard:plan:always')
t0 = time.perf_counter()
res = elastic.live_reshape(
    jax.sharding.Mesh(np.asarray(jax.devices()[:1]), ('d0',)))
wall_ms = (time.perf_counter() - t0) * 1000.0
faults.configure(None)
assert res['mode'] == mode, res
assert mesh_mod.get_mesh().devices.size == 1
for step in (4, 5, 6):
    x = x * 1.000001 + float(step)
digest = hashlib.sha256(np.ascontiguousarray(np.asarray(x))
                        .tobytes()).hexdigest()
print('RESHAPE_DIGEST mode=%s %s wall_ms=%.1f' % (mode, digest, wall_ms))
"""


def run_reshard_leg() -> int:
    """2-rank staged reshard round-trip (byte-identical, ledger-bounded,
    rank-skewed fault rolls back coherently), then a single-rank live
    2-device -> 1-device mesh reshape byte-identical to the
    checkpoint-fallback path."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_reshard_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    def base_env():
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_TRACE"] = trace_base
        # tripwire: a mispaired stage collective hangs, and that must
        # fail the leg as a stall instead of wedging CI
        env["RAMBA_WATCHDOG_S"] = "60"
        return env

    # --- phase 1: 2-rank SPMD round-trip + rank-skewed fault ---
    procs, logs = [], []
    for rank in range(2):
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _RESHARD_SPMD_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=base_env(), stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))
    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()
    ok = all(rc == 0 for rc in rcs)

    digests, fault_digests = {}, {}
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        joined = "\n".join(tail)
        for ln in tail:
            if ln.startswith(f"RESHARD_LEG_DIGEST rank={rank} "):
                digests[rank] = ln.split()[-1]
            if ln.startswith(f"RESHARD_LEG_FAULT rank={rank} "):
                fault_digests[rank] = ln.split("digest=")[1].split()[0]
        if (f"RESHARD_LEG_DIGEST rank={rank}" not in joined
                or f"RESHARD_LEG_FAULT rank={rank}" not in joined):
            ok = False
        print(f"--- reshard leg phase 1 rank {rank} rc={rcs[rank]} "
              f"({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    if ok and (digests[0] != digests[1]
               or fault_digests[0] != fault_digests[1]):
        print(f"reshard leg: FAIL (rank digests diverge: {digests}, "
              f"post-fault {fault_digests})")
        ok = False

    # Per-rank traces must carry the reshard timeline: the fenced plan,
    # its stages, and the coherent rollback from the fault phase.
    import json

    if ok:
        for rank in range(2):
            path = f"{trace_base}.rank{rank}"
            try:
                with open(path) as f:
                    evs = [json.loads(ln) for ln in f if ln.strip()]
                n_plan = sum(1 for e in evs if e.get("type") == "reshard"
                             and e.get("action") == "plan")
                n_stage = sum(1 for e in evs if e.get("type") == "reshard"
                              and e.get("action") == "stage")
                n_roll = sum(1 for e in evs if e.get("type") == "reshard"
                             and e.get("action") == "rollback")
                n_stall = sum(1 for e in evs if e.get("type") == "stall")
                print(f"reshard leg rank {rank}: {n_plan} plans, "
                      f"{n_stage} stages, {n_roll} rollbacks, "
                      f"{n_stall} stalls")
                if n_plan < 6 or n_stage < 6 or n_roll != 1 or n_stall:
                    print(f"reshard leg rank {rank}: FAIL (timeline "
                          f"plan={n_plan} stage={n_stage} roll={n_roll} "
                          f"stall={n_stall})")
                    ok = False
            except (OSError, ValueError) as e:
                print(f"reshard leg rank {rank}: FAIL ({e})")
                ok = False

    # --- phase 2: single-rank live 2->1 reshape vs checkpoint path ---
    reshape = {}
    if ok:
        for mode in ("live", "checkpoint"):
            env = base_env()
            env.pop("RAMBA_TRACE", None)
            r = subprocess.run(
                [sys.executable, "-c", _RESHARD_LIVE_WORKLOAD, mode],
                env=env, capture_output=True, text=True, cwd=REPO,
                timeout=budget,
            )
            print(f"--- reshard leg reshape[{mode}] rc={r.returncode} ---")
            out = r.stdout.splitlines()
            print("\n".join(out[-4:]) if r.returncode == 0
                  else (r.stdout + r.stderr))
            if r.returncode != 0:
                ok = False
                continue
            for ln in out:
                if ln.startswith(f"RESHAPE_DIGEST mode={mode} "):
                    reshape[mode] = ln.split()[2]
            if mode not in reshape:
                print(f"reshard leg: FAIL (no digest from {mode} reshape)")
                ok = False
    if ok:
        if reshape["live"] != reshape["checkpoint"]:
            print(f"reshard leg: FAIL (live reshape digest "
                  f"{reshape['live']} != checkpoint path "
                  f"{reshape['checkpoint']})")
            ok = False
        else:
            print(f"reshard leg: live 2->1 mesh reshape is byte-identical "
                  f"to the checkpoint path "
                  f"(sha256 {reshape['live'][:16]}...)")

    print(f"two-process reshard leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    else:
        print(f"reshard leg artifacts kept at {basetemp}")
    return 0 if ok else 1


def run_elastic_leg() -> int:
    """2-rank auto-checkpoint mid-workload, then a 1-rank resume (mesh
    reshape) finishes it; the final state must be byte-identical to a
    straight 1-rank run."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_elastic_")
    ckpt_root = os.path.join(basetemp, "ckpts")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    def base_env():
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_TRACE"] = trace_base
        # armed but generous: nothing here should stall, and a hang in
        # the checkpoint barrier must fail the leg instead of wedging CI
        env["RAMBA_WATCHDOG_S"] = "60"
        return env

    # --- phase 1: 2-rank run, auto-checkpoint at step 2, stop ---
    procs, logs = [], []
    for rank in range(2):
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _ELASTIC_SPMD_WORKLOAD, str(rank),
             f"localhost:{port}", ckpt_root],
            env=base_env(), stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))
    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()
    ok = all(rc == 0 for rc in rcs)
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        joined = "\n".join(tail)
        if (f"ELASTIC_LEG_SAVED rank={rank} step=2" not in joined
                or f"ELASTIC_LEG_PHASE1_OK rank={rank}" not in joined):
            ok = False
        print(f"--- elastic leg phase 1 rank {rank} rc={rcs[rank]} "
              f"({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))

    # --- phase 2: 1-rank resume finishes; reference runs straight ---
    digests = {}
    if ok:
        for name, code, argv in (
            ("resume", _ELASTIC_RESUME_WORKLOAD, [ckpt_root]),
            ("reference", _ELASTIC_REF_WORKLOAD, []),
        ):
            env = base_env()
            r = subprocess.run(
                [sys.executable, "-c", code, *argv],
                env=env, capture_output=True, text=True, cwd=REPO,
                timeout=budget,
            )
            print(f"--- elastic leg {name} rc={r.returncode} ---")
            out = r.stdout.splitlines()
            print("\n".join(out[-4:]) if r.returncode == 0
                  else (r.stdout + r.stderr))
            if r.returncode != 0:
                ok = False
                continue
            for line in out:
                if line.startswith(("ELASTIC_LEG_DIGEST ",
                                    "ELASTIC_LEG_REF ")):
                    digests[name] = line.split(" ", 1)[1].strip()
            if name not in digests:
                print(f"elastic leg: FAIL (no digest from {name})")
                ok = False

    if ok:
        if digests["resume"] != digests["reference"]:
            print("elastic leg: FAIL (resume digest "
                  f"{digests['resume']} != reference "
                  f"{digests['reference']})")
            ok = False
        else:
            print(f"elastic leg: resume after mesh reshape 2->1 is "
                  f"byte-identical (sha256 {digests['resume'][:16]}...)")

    # The per-rank traces must carry the lifecycle story: heartbeats and
    # the checkpoint_saved event from phase 1.
    import json

    if ok:
        for rank in range(2):
            path = f"{trace_base}.rank{rank}"
            try:
                with open(path) as f:
                    evs = [json.loads(ln) for ln in f if ln.strip()]
                n_beat = sum(1 for e in evs if e.get("type") == "heartbeat")
                n_saved = sum(1 for e in evs if e.get("type") == "lifecycle"
                              and e.get("phase") == "checkpoint_saved")
                print(f"elastic leg rank {rank}: {len(evs)} events, "
                      f"{n_beat} heartbeats, {n_saved} checkpoint_saved")
                if n_beat == 0 or n_saved == 0:
                    print(f"elastic leg rank {rank}: FAIL "
                          f"(beats={n_beat}, saved={n_saved})")
                    ok = False
            except (OSError, ValueError) as e:
                print(f"elastic leg rank {rank}: FAIL ({e})")
                ok = False

    print(f"two-process elastic leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_serving_leg() -> int:
    """Two ranks drive serving sessions in deterministic lockstep; the
    coalesced-batch fingerprint and the full kernel-key sets must be
    identical across ranks."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_serve_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_TRACE"] = trace_base
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _SERVING_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    # Both ranks must agree on the coalesced-program fingerprint AND the
    # full kernel-key set — SPMD serving means identical dispatch.
    marks = {"SERVING_LEG_COALESCE": [None, None],
             "SERVING_LEG_KEYS": [None, None]}
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        for line in tail:
            for mark in marks:
                if line.startswith(f"{mark} rank={rank} "):
                    marks[mark][rank] = line.split(" ", 2)[2]
        if any(marks[m][rank] is None for m in marks):
            ok = False
        print(f"--- serving leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    for mark, (r0, r1) in marks.items():
        if ok and r0 != r1:
            print(f"serving leg: FAIL ({mark} diverges: r0={r0} r1={r1})")
            ok = False
    if ok:
        nkeys = len((marks["SERVING_LEG_KEYS"][0] or "").split(","))
        print(f"serving leg: coalesced {marks['SERVING_LEG_COALESCE'][0]}, "
              f"{nkeys} kernel keys, identical on both ranks")

    # The per-rank traces must carry the tenant-tagged serving events.
    import json

    for rank in range(2):
        path = f"{trace_base}.rank{rank}"
        try:
            with open(path) as f:
                evs = [json.loads(ln) for ln in f if ln.strip()]
            n_co = sum(1 for e in evs if e.get("type") == "serve_coalesce")
            n_tenant = sum(1 for e in evs if e.get("type") == "flush"
                           and e.get("tenant") == "spmd")
            print(f"serving leg rank {rank}: {len(evs)} events, "
                  f"{n_co} coalesce, {n_tenant} tenant-tagged flushes")
            if n_co == 0 or n_tenant == 0:
                print(f"serving leg rank {rank}: FAIL "
                      f"(coalesce={n_co}, tenant-flushes={n_tenant})")
                ok = False
        except (OSError, ValueError) as e:
            print(f"serving leg rank {rank}: FAIL ({e})")
            ok = False

    print(f"two-process serving leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_telemetry_leg() -> int:
    """Two ranks share ONE trace_id across their serving sessions, serve
    /metrics concurrently, and scrape themselves; rank labels must be
    distinct and the shared trace must land in both ranks' traces."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_telem_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    shared_trace = "feedfacefeedface"
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET",
                  "RAMBA_METRICS_PORT", "RAMBA_METRICS_FILE",
                  "RAMBA_FLIGHT_DIR"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_TRACE"] = trace_base
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _TELEMETRY_WORKLOAD, str(rank),
             f"localhost:{port}", shared_trace],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    # Each rank's scrape must be labeled with its OWN rank — concurrent
    # exporters on one host stay distinguishable after aggregation.
    labels = [None, None]
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        for line in tail:
            if line.startswith(f"TELEMETRY_LEG_SCRAPE rank={rank} "):
                labels[rank] = line.split("labels=")[1].split(" ")[0]
        if labels[rank] is None:
            ok = False
        print(f"--- telemetry leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    if ok:
        if labels[0] == labels[1] or labels != [str(r) for r in range(2)]:
            print(f"telemetry leg: FAIL (rank labels not distinct: "
                  f"r0={labels[0]} r1={labels[1]})")
            ok = False
        else:
            print(f"telemetry leg: scrapes labeled rank={labels[0]} / "
                  f"rank={labels[1]}, distinct")

    # One request, two ranks: the shared trace_id must appear in BOTH
    # per-rank event files — what --trace needs to merge the story.
    import json

    for rank in range(2):
        path = f"{trace_base}.rank{rank}"
        try:
            with open(path) as f:
                evs = [json.loads(ln) for ln in f if ln.strip()]
            traced = [e for e in evs if e.get("trace_id") == shared_trace
                      or shared_trace in (e.get("trace_ids") or [])]
            kinds = sorted({e.get("type", "?") for e in traced})
            print(f"telemetry leg rank {rank}: {len(evs)} events, "
                  f"{len(traced)} in trace {shared_trace} ({','.join(kinds)})")
            if not traced:
                print(f"telemetry leg rank {rank}: FAIL (shared trace "
                      f"missing)")
                ok = False
        except (OSError, ValueError) as e:
            print(f"telemetry leg rank {rank}: FAIL ({e})")
            ok = False

    # And the cross-rank causal chain must actually reconstruct.
    if ok:
        merged = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
             trace_base, "--trace", shared_trace],
            capture_output=True, text=True, cwd=REPO,
        )
        print(merged.stdout.strip())
        if merged.returncode != 0 or "2 process(es)" not in merged.stdout:
            print(f"telemetry leg: FAIL (--trace rc={merged.returncode})")
            print(merged.stderr.strip())
            ok = False

    print(f"two-process telemetry leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_fleet_leg() -> int:
    """Fleet observability federation acceptance (PR 16): three
    INDEPENDENT replica processes publish into one snapshot spool.  The
    collector must (a) prove every live replica healthy with lockstep
    kernel fingerprints, (b) reconcile the fleet goodput rollup against
    the per-replica spool documents within 1%, (c) classify an injected
    torn document without crashing, (d) flag a replica killed mid-soak
    dead within 2x the publish interval, and (e) the stitched --trace
    view over the per-replica trace dirs must span the replicas."""
    import json
    import signal

    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_fleet_")
    fleet_dir = os.path.join(basetemp, "fleet")
    traces = os.path.join(basetemp, "traces")
    interval = 0.2
    soak_s = 120.0
    shared_trace = "feedfacef1ee70001"
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))
    n = 3
    collector = os.path.join(REPO, "scripts", "fleet_collector.py")

    procs, logs = [], []
    for idx in range(n):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET",
                  "RAMBA_METRICS_PORT", "RAMBA_METRICS_FILE",
                  "RAMBA_FLIGHT_DIR", "RAMBA_FLEET_DIR",
                  "RAMBA_FLEET_INTERVAL_S", "RAMBA_FLEET_STALE_X",
                  "RAMBA_FLEET_DEAD_X"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_FLEET_DIR"] = fleet_dir
        env["RAMBA_FLEET_INTERVAL_S"] = str(interval)
        tdir = os.path.join(traces, f"replica{idx}")
        os.makedirs(tdir, exist_ok=True)
        env["RAMBA_TRACE"] = os.path.join(tdir, "trace.jsonl")
        log = open(os.path.join(basetemp, f"replica{idx}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _FLEET_WORKLOAD, str(idx),
             shared_trace, str(soak_s)],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    ok = True
    deadline = time.time() + budget

    def _tail(idx):
        with open(os.path.join(basetemp, f"replica{idx}.log")) as f:
            return f.read().splitlines()

    def _collect(expect_rc, phase):
        nonlocal ok
        r = subprocess.run(
            [sys.executable, collector, fleet_dir, "--json"],
            capture_output=True, text=True, cwd=REPO,
        )
        doc = None
        try:
            doc = json.loads(r.stdout)
        except ValueError:
            pass
        if "Traceback" in r.stderr or doc is None:
            print(f"fleet leg: FAIL ({phase}: collector crashed)")
            print(r.stdout[-2000:] + r.stderr[-2000:])
            ok = False
        elif r.returncode != expect_rc:
            print(f"fleet leg: FAIL ({phase}: collector rc={r.returncode}, "
                  f"want {expect_rc})")
            print(json.dumps(doc.get("health", {}), indent=2)[:2000])
            ok = False
        return doc

    # -- phase A: every replica publishes and goes healthy -------------------
    fps = [None] * n
    while time.time() < deadline and any(f is None for f in fps):
        for idx in range(n):
            if fps[idx] is not None:
                continue
            for line in _tail(idx):
                if line.startswith(f"FLEET_REPLICA_OK idx={idx}"):
                    fps[idx] = line.split("fps=")[1].strip()
            if fps[idx] is None and procs[idx].poll() is not None:
                print(f"fleet leg: FAIL (replica {idx} exited "
                      f"rc={procs[idx].returncode} before READY)")
                print("\n".join(_tail(idx)[-40:]))
                ok = False
                deadline = 0  # bail out of the wait loop
        if ok and any(f is None for f in fps):
            time.sleep(0.1)
    if ok and any(f is None for f in fps):
        print(f"fleet leg: FAIL (timeout waiting for READY markers {fps})")
        ok = False

    if ok:
        if not fps[0] or len(set(fps)) != 1:
            print(f"fleet leg: FAIL (kernel fingerprints not lockstep: "
                  f"{fps})")
            ok = False
        else:
            print(f"fleet leg: {n} replicas ready, lockstep kernel "
                  f"fingerprints [{fps[0]}]")

    if ok:
        doc = _collect(0, "healthy fleet")
        if ok:
            h = doc["health"]
            if (h["fleet_state"] != "healthy"
                    or h["counts"]["healthy"] != n):
                print(f"fleet leg: FAIL (want {n} healthy, got "
                      f"{h['counts']} fleet_state={h['fleet_state']})")
                ok = False
            else:
                ages = [r["age_s"] for r in h["replicas"].values()]
                print(f"fleet leg: collector proves {n} healthy "
                      f"(max snapshot age {max(ages):.2f}s)")

        # rollup reconciliation: fleet goodput vs the raw spool documents
        if ok:
            raw_flushes = raw_nodes = 0
            for f in sorted(os.listdir(fleet_dir)):
                with open(os.path.join(fleet_dir, f)) as fh:
                    d = json.load(fh)
                counters = d["diagnostics"]["counters"]
                raw_flushes += int(counters.get("fuser.flushes", 0))
                raw_nodes += int(counters.get("fuser.nodes_flushed", 0))
            gp = doc["rollup"]["goodput"]
            per_rep_sum = sum(r["flushes"]
                              for r in gp["replicas"].values())
            drift = abs(gp["flushes"] - raw_flushes) \
                / max(1, raw_flushes)
            if (gp["flushes"] != per_rep_sum or drift > 0.01
                    or raw_flushes == 0):
                print(f"fleet leg: FAIL (rollup {gp['flushes']} != "
                      f"per-replica {per_rep_sum} / raw {raw_flushes})")
                ok = False
            else:
                print(f"fleet leg: rollup reconciles (fleet "
                      f"flushes={gp['flushes']} == raw spool sum "
                      f"{raw_flushes}, nodes={raw_nodes})")

    # -- phase B: stitched cross-process trace -------------------------------
    if ok:
        merged = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts",
                                          "trace_report.py"),
             traces, "--trace", shared_trace],
            capture_output=True, text=True, cwd=REPO,
        )
        print(merged.stdout.strip())
        if (merged.returncode != 0
                or f"{n} process(es)" not in merged.stdout):
            print(f"fleet leg: FAIL (--trace over {traces} "
                  f"rc={merged.returncode})")
            print(merged.stderr.strip())
            ok = False

    # -- phase C: torn document never crashes the collector ------------------
    if ok:
        torn = os.path.join(fleet_dir, "torn-deadbeef-0.json")
        with open(torn, "w") as f:
            f.write('{"schema_version": 1, "replica": "torn-deadbe')
        doc = _collect(2, "torn document")  # stale present -> rc 2
        if ok:
            row = doc["health"]["replicas"].get("torn-deadbeef-0")
            if row is None or row["state"] != "stale":
                print(f"fleet leg: FAIL (torn doc classified {row})")
                ok = False
            else:
                print(f"fleet leg: torn document classified stale "
                      f"({row['reason']}), no crash")
        os.unlink(torn)

    # -- phase D: replica killed mid-soak goes dead within 2x interval -------
    if ok:
        victim = n - 1
        procs[victim].send_signal(signal.SIGKILL)
        procs[victim].wait(timeout=30)
        t_kill = time.monotonic()
        # the last publish predates the kill, so the snapshot's age
        # crosses the dead threshold no later than kill + 2x interval
        time.sleep(2.0 * interval)
        doc = _collect(3, "dead replica")  # dead present -> rc 3
        elapsed = time.monotonic() - t_kill
        if ok:
            dead = [rep for rep, r in doc["health"]["replicas"].items()
                    if r["state"] == "dead"]
            counts = doc["health"]["counts"]
            if len(dead) != 1 or counts["healthy"] != n - 1:
                print(f"fleet leg: FAIL (want 1 dead / {n - 1} healthy "
                      f"{elapsed:.2f}s after kill, got {counts})")
                ok = False
            else:
                age = doc["health"]["replicas"][dead[0]]["age_s"]
                print(f"fleet leg: killed replica {dead[0]} flagged dead "
                      f"at the first scrape past 2x interval "
                      f"({elapsed:.2f}s after SIGKILL, snapshot age "
                      f"{age:.2f}s, dead threshold {2 * interval:.1f}s)")

    for p in procs:
        if p.poll() is None:
            p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
    for log in logs:
        log.close()
    print(f"fleet leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


# Router-leg driver (PR 17): runs in its OWN subprocess so the router's
# redirect/heal events stream into a dedicated RAMBA_TRACE file that the
# stitched trace view can interleave with the replicas'.  Spawns replica
# servers via scripts/fleet_router.py and walks the serving plane through
# four phases, printing one ROUTER_* marker line per phase for the leg
# runner to assert on.  argv: <traces_dir>.
_ROUTER_DRIVER = """
import os
import sys
import time

traces = sys.argv[1]
sys.path.insert(0, os.path.join(os.environ["PYTHONPATH"], "scripts"))
import fleet_router

from ramba_tpu.fleet.router import Router

TRACE = "deadbeefcafe0001"
SEQ = [("init", {"name": "x", "shape": [256], "fill": 2.0})] + [
    ("affine", {"name": "x", "a": 1.01, "b": float(i)}) for i in range(4)]


def spawn(idx, extra=None):
    tdir = os.path.join(traces, "replica%d" % idx)
    os.makedirs(tdir, exist_ok=True)
    env = {"RAMBA_TRACE": os.path.join(tdir, "trace.jsonl")}
    env.update(extra or {})
    return fleet_router.spawn_replica(env)


def run_session(router, tenant, trace_id=None):
    sid = router.open_session(tenant=tenant, trace_id=trace_id)
    for w, p in SEQ:
        router.step(sid, w, p)
    digest = router.step(sid, "digest")["result"]
    router.close_session(sid)
    return digest


def stop(router, *procs):
    router.shutdown_fleet()
    for p in procs:
        try:
            p.wait(timeout=30)
        except Exception:
            p.kill()


# phase 1: one cold replica pays every compile, fills the shared tier,
# and defines the no-fault reference digest (the workload registry is
# deterministic, so this digest is THE answer for every later phase)
p0, ep0 = spawn(0)
r0 = Router(endpoints=[ep0])
ref = [run_session(r0, t) for t in ("acme", "globex")]
assert len(set(ref)) == 1, ref
c0 = r0.call_replica(ep0, "stats")["counters"]
saved = r0.call_replica(ep0, "save_artifacts", k=16)["saved"]
stop(r0, p0)
print("ROUTER_REF digest=%s compiles=%d aot_stored=%d" % (
    ref[0], c0["fuser.compiles"], saved.get("stored", 0)), flush=True)

# phase 2: cold process, shared AOT tier on but the shared memo lane
# OFF -- every flush demand-compiles, and the compiler must be fed by
# replica 0's persisted executables (cross-writer AOT hits)
p1, ep1 = spawn(1, {"RAMBA_MEMO_SHARED": "0"})
r1 = Router(endpoints=[ep1])
d1 = [run_session(r1, t) for t in ("acme", "globex")]
c1 = r1.call_replica(ep1, "stats")["counters"]
stop(r1, p1)
print("ROUTER_WARM_AOT ok=%d cross=%d compiles=%d" % (
    int(d1 == ref), c1["compile.persist_cross_hit"],
    c1["fuser.compiles"]), flush=True)

# phase 3: cold process, shared memo lane ON -- flushes hit replica 0's
# content-addressed memo blobs and skip the compiler
p2, ep2 = spawn(2)
r2 = Router(endpoints=[ep2])
d2 = [run_session(r2, t) for t in ("acme", "globex")]
c2 = r2.call_replica(ep2, "stats")["counters"]
stop(r2, p2)
print("ROUTER_WARM_MEMO ok=%d shared=%d compiles=%d" % (
    int(d2 == ref), c2["memo.shared_hit"], c2["fuser.compiles"]),
    flush=True)

# phase 4: two replicas, four tenants; SIGKILL the replica serving
# tenant acme mid-soak -- its sessions must redirect off the corpse
# (trip the fleet breaker), heal by deterministic replay on the
# survivor, and finish byte-identical to the phase-1 reference
procs = {}
p3, ep3 = spawn(3)
p4, ep4 = spawn(4)
procs[ep3], procs[ep4] = p3, p4
rt = Router(endpoints=[ep3, ep4])
tenants = ("acme", "globex", "initech", "umbrella")
sids = {t: rt.open_session(
            tenant=t, trace_id=(TRACE if t == "acme" else None))
        for t in tenants}
victim = None
for i, (w, p) in enumerate(SEQ):
    for t in tenants:
        rt.step(sids[t], w, p)
    if i == 1:
        victim = rt.stats()["sessions"][sids["acme"]]["endpoint"]
        procs[victim].kill()
        procs[victim].wait(timeout=30)
d4 = [rt.step(sids[t], "digest")["result"] for t in tenants]
st = rt.stats()
trips = st["replicas"][victim]["breaker"]["trips"]
survivor = ep4 if victim == ep3 else ep3
c4 = rt.call_replica(survivor, "stats")["counters"]
stop(rt, procs[survivor])
print("ROUTER_HEAL ok=%d redirects=%d heals=%d trips=%d "
      "surv_shared=%d trace=%s" % (
          int(all(d == ref[0] for d in d4)), st["redirects"],
          st["heals"], trips, c4["memo.shared_hit"], TRACE), flush=True)
print("ROUTER_DRIVER_OK", flush=True)
"""


def run_router_leg() -> int:
    """Fleet serving-plane acceptance (PR 17): a router process drives
    five replica servers (spawned/killed across four phases) against one
    snapshot spool + shared artifact tier.  Asserts (a) a cold replica
    compiles and persists, (b) a second cold replica comes up WARM off
    the shared AOT tier (cross-writer persist hits, byte-identical
    digests), (c) a third comes up warm off the shared memo lane with
    near-zero demand compiles, (d) a replica SIGKILLed mid-soak trips
    the router's fleet breaker, its tenants redirect + heal by replay
    onto the survivor with byte-identical digests, and (e) the stitched
    trace over router + replica trace files tells the redirect/heal
    story."""
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_router_")
    fleet_dir = os.path.join(basetemp, "fleet")
    traces = os.path.join(basetemp, "traces")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "900"))

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
              "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
              "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET",
              "RAMBA_METRICS_PORT", "RAMBA_METRICS_FILE",
              "RAMBA_FLIGHT_DIR", "RAMBA_FLEET_DIR",
              "RAMBA_FLEET_INTERVAL_S", "RAMBA_FLEET_STALE_X",
              "RAMBA_FLEET_DEAD_X", "RAMBA_FLEET_ENDPOINT",
              "RAMBA_FLEET_AUTHKEY", "RAMBA_ARTIFACTS", "RAMBA_CACHE",
              "RAMBA_AOT", "RAMBA_MEMO", "RAMBA_MEMO_SHARED",
              "RAMBA_MEMO_SHARED_MAX", "RAMBA_HANDOFF_DIR",
              "RAMBA_ROUTER_TIMEOUT_S", "RAMBA_ROUTER_HEDGE",
              "RAMBA_ROUTER_HEDGE_FACTOR", "RAMBA_ROUTER_MAX_REDIRECTS",
              "RAMBA_BREAKER_THRESHOLD", "RAMBA_TRACE"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["RAMBA_FLEET_DIR"] = fleet_dir
    env["RAMBA_FLEET_INTERVAL_S"] = "0.2"
    env["RAMBA_ARTIFACTS"] = os.path.join(basetemp, "artifacts")
    env["RAMBA_CACHE"] = os.path.join(basetemp, "aot")  # shared AOT tier
    env["RAMBA_MEMO"] = "1"
    env["RAMBA_BREAKER_THRESHOLD"] = "1"  # first failure trips
    env["RAMBA_ROUTER_TIMEOUT_S"] = "10"
    rdir = os.path.join(traces, "router")
    os.makedirs(rdir, exist_ok=True)
    env["RAMBA_TRACE"] = os.path.join(rdir, "trace.jsonl")

    log_path = os.path.join(basetemp, "driver.log")
    with open(log_path, "w") as log:
        proc = subprocess.Popen(
            [sys.executable, "-c", _ROUTER_DRIVER, traces],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO)
        try:
            rc = proc.wait(timeout=budget)
        except subprocess.TimeoutExpired:
            proc.kill()
            rc = -9
    with open(log_path) as f:
        lines = f.read().splitlines()
    marks = {}
    for ln in lines:
        if ln.startswith("ROUTER_"):
            parts = ln.split()
            marks[parts[0]] = dict(
                kv.split("=", 1) for kv in parts[1:] if "=" in kv)

    ok = rc == 0 and "ROUTER_DRIVER_OK" in marks
    if not ok:
        print(f"router leg: FAIL (driver rc={rc}, markers "
              f"{sorted(marks)})")
        print("\n".join(lines[-60:]))

    def _ints(mark):
        return {k: int(v) for k, v in marks[mark].items()
                if v.lstrip("-").isdigit()}

    if ok:
        ref = _ints("ROUTER_REF")
        if ref["compiles"] == 0 or ref["aot_stored"] == 0:
            print(f"router leg: FAIL (cold replica should compile and "
                  f"persist, got {marks['ROUTER_REF']})")
            ok = False
        else:
            print(f"router leg: cold replica paid {ref['compiles']} "
                  f"compiles, persisted {ref['aot_stored']} AOT blobs, "
                  f"reference digest {marks['ROUTER_REF']['digest'][:16]}")

    if ok:
        aot = _ints("ROUTER_WARM_AOT")
        if not aot["ok"] or aot["cross"] == 0:
            print(f"router leg: FAIL (AOT-warm replica: want "
                  f"byte-identical digests + cross-writer persist hits, "
                  f"got {marks['ROUTER_WARM_AOT']})")
            ok = False
        else:
            print(f"router leg: replica 2 warm off the shared AOT tier "
                  f"({aot['cross']} cross-writer hits, "
                  f"{aot['compiles']} demand compiles, digests match)")

    if ok:
        memo = _ints("ROUTER_WARM_MEMO")
        if (not memo["ok"] or memo["shared"] == 0
                or memo["compiles"] >= ref["compiles"]):
            print(f"router leg: FAIL (memo-warm replica: want "
                  f"byte-identical digests, >0 shared memo hits, fewer "
                  f"compiles than cold ({ref['compiles']}), got "
                  f"{marks['ROUTER_WARM_MEMO']})")
            ok = False
        else:
            print(f"router leg: replica 3 warm off the shared memo lane "
                  f"({memo['shared']} cross-replica memo hits, "
                  f"{memo['compiles']} vs cold {ref['compiles']} demand "
                  f"compiles, digests match)")

    if ok:
        heal = _ints("ROUTER_HEAL")
        if (not heal["ok"] or heal["redirects"] == 0
                or heal["heals"] == 0 or heal["trips"] == 0):
            print(f"router leg: FAIL (kill mid-soak: want byte-identical "
                  f"digests + redirects + heals + breaker trips, got "
                  f"{marks['ROUTER_HEAL']})")
            ok = False
        else:
            print(f"router leg: SIGKILL mid-soak healed "
                  f"({heal['redirects']} redirects, {heal['heals']} "
                  f"replay heals, {heal['trips']} breaker trips, "
                  f"survivor made {heal['surv_shared']} shared memo "
                  f"hits, all 4 tenants byte-identical)")

    # stitched trace: router + replica files interleave, and the
    # redirect/heal story is visible in the merged noteworthy stream
    if ok:
        merged = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "trace_report.py"),
             traces, "--merge-ranks"],
            capture_output=True, text=True, cwd=REPO)
        if (merged.returncode != 0 or "redirect" not in merged.stdout
                or "heal" not in merged.stdout):
            print(f"router leg: FAIL (--merge-ranks rc="
                  f"{merged.returncode} must show the redirect/heal "
                  f"story)")
            print(merged.stdout[-2000:] + merged.stderr[-2000:])
            ok = False
        else:
            note = [ln for ln in merged.stdout.splitlines()
                    if "redirect" in ln or "heal" in ln]
            print("router leg: stitched trace shows the failover story:")
            print("\n".join(f"  {ln.strip()}" for ln in note[:6]))

    if ok:
        chain = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "scripts", "trace_report.py"),
             traces, "--trace", marks["ROUTER_HEAL"]["trace"]],
            capture_output=True, text=True, cwd=REPO)
        if chain.returncode != 0 or "process(es)" not in chain.stdout:
            print(f"router leg: FAIL (--trace "
                  f"{marks['ROUTER_HEAL']['trace']} rc="
                  f"{chain.returncode})")
            print(chain.stdout[-2000:] + chain.stderr[-2000:])
            ok = False
        else:
            head = chain.stdout.splitlines()[0]
            print(f"router leg: {head.strip()}")

    print(f"router leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_perf_leg() -> int:
    """Two ranks under RAMBA_PERF=1; both ledgers must report the same
    kernel fingerprint set, and the merged timeline must build."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_perf_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_PERF"] = "1"
        env["RAMBA_TRACE"] = trace_base
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _PERF_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    # Both ranks' ledgers must report the identical kernel-key set:
    # fingerprints are structure-stable, so SPMD lockstep => equal sets.
    keysets = [None, None]
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        for line in tail:
            if line.startswith(f"PERF_LEG_KEYS rank={rank} "):
                keysets[rank] = line.split(" ", 2)[2]
        if keysets[rank] is None:
            ok = False
        print(f"--- perf leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    if ok and keysets[0] != keysets[1]:
        print(f"perf leg: FAIL (kernel keys diverge: "
              f"r0={keysets[0]} r1={keysets[1]})")
        ok = False
    elif ok:
        nkeys = len((keysets[0] or "").split(","))
        print(f"perf leg: {nkeys} kernel keys, identical on both ranks")

    # The cross-rank merged timeline must build from the per-rank traces
    # and see both ranks in lockstep.
    if ok:
        merged = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
             trace_base, "--merge-ranks"],
            capture_output=True, text=True, cwd=REPO,
        )
        print(merged.stdout.strip())
        if (merged.returncode != 0
                or "2 rank(s)" not in merged.stdout
                or "rank divergence: none" not in merged.stdout):
            print(f"perf leg: FAIL (merge-ranks rc={merged.returncode})")
            print(merged.stderr.strip())
            ok = False

    print(f"two-process perf leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_attrib_leg() -> int:
    """Two ranks under RAMBA_PERF=1 + a pinned peak table; both must
    stamp lockstep stage signatures, classify every shared fingerprint
    identically on the roofline, and reconcile stage sums with span
    wall; the stage waterfall and merged stage columns must build."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_attrib_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET",
                  "RAMBA_BASELINE_DIR"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_PERF"] = "1"
        env["RAMBA_TRACE"] = trace_base
        # same denominators on both ranks: classification must agree by
        # construction, not by both hosts happening to probe alike
        env["RAMBA_PEAKS_JSON"] = (
            '{"default": {"peak_gbps": 100.0, "peak_tflops": 1.0}}')
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _ATTRIB_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    marks = {"ATTRIB_LEG_STAGES": [None, None],
             "ATTRIB_LEG_ROOFS": [None, None]}
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        for line in tail:
            for key in marks:
                if line.startswith(f"{key} rank={rank} "):
                    marks[key][rank] = line.split(" ", 2)[2]
        if any(marks[key][rank] is None for key in marks):
            ok = False
        print(f"--- attrib leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    for key, vals in marks.items():
        if ok and vals[0] != vals[1]:
            print(f"attrib leg: FAIL ({key} diverges: "
                  f"r0={vals[0]} r1={vals[1]})")
            ok = False
    if ok:
        nflush = len((marks["ATTRIB_LEG_STAGES"][0] or "").split(";"))
        nroof = len((marks["ATTRIB_LEG_ROOFS"][0] or "").split(","))
        print(f"attrib leg: {nflush} lockstep stage signature(s), "
              f"{nroof} roofline class(es), identical on both ranks")

    # The stage waterfall and the merged stage columns must build from
    # the per-rank traces with no rank divergence.
    if ok:
        waterfall = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
             trace_base, "--attrib"],
            capture_output=True, text=True, cwd=REPO,
        )
        print(waterfall.stdout.strip())
        if (waterfall.returncode != 0
                or "stage waterfall" not in waterfall.stdout):
            print(f"attrib leg: FAIL (--attrib rc={waterfall.returncode})")
            print(waterfall.stderr.strip())
            ok = False
    if ok:
        merged = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "trace_report.py"),
             trace_base, "--merge-ranks"],
            capture_output=True, text=True, cwd=REPO,
        )
        print(merged.stdout.strip())
        if (merged.returncode != 0
                or "rank divergence: none" not in merged.stdout
                or "stage seconds per rank:" not in merged.stdout):
            print(f"attrib leg: FAIL (merge-ranks rc={merged.returncode})")
            print(merged.stderr.strip())
            ok = False

    print(f"two-process attrib leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_sampling_leg() -> int:
    """Two ranks under RAMBA_ATTRIB=sample:4 + RAMBA_TRACE_SAMPLE=4 with
    a rank-skewed execute:delay fault; the fence sequence numbers and
    roofline bounds must be identical across ranks (the sampler is
    count-derived, never timing-derived), the tail latch must replay the
    seeded slow flush's full chain into each rank's file, and steady-
    state file volume must drop >= 4x."""
    import json

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_sampling_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_HBM_BUDGET",
                  "RAMBA_BASELINE_DIR", "RAMBA_SLO_P95_MS"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_PERF"] = "1"
        env["RAMBA_TRACE"] = trace_base
        env["RAMBA_ATTRIB"] = "sample:4"
        env["RAMBA_TRACE_SAMPLE"] = "4"
        # slack against scheduler hiccups on the un-delayed rank: only
        # the seeded 400 ms flush (>= 10x any p50 here) may trip
        env["RAMBA_SLOW_FLUSH_FACTOR"] = "8"
        # rank-skewed slowness: same env on BOTH ranks (the per-site
        # call counter must advance everywhere), fires on rank 1 only
        env["RAMBA_FAULTS"] = "execute:delay:ms=40:rank=1"
        # same denominators on both ranks (see attrib leg)
        env["RAMBA_PEAKS_JSON"] = (
            '{"default": {"peak_gbps": 100.0, "peak_tflops": 1.0}}')
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _SAMPLING_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    marks = {"SAMPLING_LEG_FENCES": [None, None],
             "SAMPLING_LEG_ROOFS": [None, None],
             "SAMPLING_LEG_HEALTH": [None, None]}
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        for line in tail:
            for key in marks:
                if line.startswith(f"{key} rank={rank} "):
                    marks[key][rank] = line.split(" ", 2)[2]
        if any(marks[key][rank] is None for key in marks):
            ok = False
        print(f"--- sampling leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))

    # lockstep proof: identical fence sequence numbers per fingerprint
    # and identical roofline bounds, despite the rank-1 delay skew
    for key in ("SAMPLING_LEG_FENCES", "SAMPLING_LEG_ROOFS"):
        vals = marks[key]
        if ok and vals[0] != vals[1]:
            print(f"sampling leg: FAIL ({key} diverges: "
                  f"r0={vals[0]} r1={vals[1]})")
            ok = False
    if ok:
        for rank in range(2):
            fields = dict(kv.split("=") for kv
                          in marks["SAMPLING_LEG_HEALTH"][rank].split())
            if fields["stalls"] != "0" or fields["local"] != "0":
                print(f"sampling leg: FAIL (rank {rank} not clean under "
                      f"skew: {fields})")
                ok = False
            if int(fields["est"]) <= 0 or int(fields["fenced"]) <= 0:
                print(f"sampling leg: FAIL (rank {rank} missing "
                      f"estimated/fenced spans: {fields})")
                ok = False
            if int(fields["latched"]) < 1:
                print(f"sampling leg: FAIL (rank {rank} tail latch never "
                      f"fired: {fields})")
                ok = False

    # file-lane checks per rank: exactly the 5 hash-selected steady
    # chains on disk (9.6x volume drop), plus the latched slow-0 chain
    # in full (6 warm flushes + the slow one + the incident line)
    if ok:
        for rank in range(2):
            fpath = f"{trace_base}.rank{rank}"
            steady_ids, slow_flushes, slow_incident = set(), 0, 0
            try:
                with open(fpath) as f:
                    for line in f:
                        try:
                            e = json.loads(line)
                        except ValueError:
                            continue
                        tid = e.get("trace_id") or ""
                        if tid.startswith("steady-"):
                            steady_ids.add(tid)
                        if tid == "slow-0":
                            if e.get("type") == "flush":
                                slow_flushes += 1
                            elif e.get("type") == "slow_flush":
                                slow_incident += 1
            except OSError as exc:
                print(f"sampling leg: FAIL (rank {rank} trace file: {exc})")
                ok = False
                continue
            if len(steady_ids) != 5:
                print(f"sampling leg: FAIL (rank {rank}: {len(steady_ids)} "
                      f"steady chains on disk, expected the 5 hash-selected "
                      f"ones: {sorted(steady_ids)})")
                ok = False
            if slow_flushes < 7 or slow_incident < 1:
                print(f"sampling leg: FAIL (rank {rank}: latched chain "
                      f"incomplete — {slow_flushes} flush spans, "
                      f"{slow_incident} slow_flush line(s))")
                ok = False
            if ok:
                print(f"sampling leg rank {rank}: 5/48 steady chains on "
                      f"disk (9.6x drop), slow-0 chain replayed "
                      f"({slow_flushes} spans + incident)")

    print(f"two-process sampling leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_memo_leg() -> int:
    """Two ranks under RAMBA_MEMO=1; both must compute the identical
    canonical hash and hit the result cache in LOCKSTEP (a hit skips
    dispatch — rank-skewed hits would mispair the post-flush gathers)."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_memo_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET",
                  "RAMBA_MEMO_BUDGET"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_MEMO"] = "1"
        env["RAMBA_TRACE"] = trace_base
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _MEMO_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    # The canonical hash is a pure function of program structure and the
    # hit/insert counts a deterministic function of the flush sequence:
    # both markers must be IDENTICAL across ranks.
    markers = [None, None]
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        for line in tail:
            if line.startswith(f"MEMO_LEG rank={rank} "):
                markers[rank] = line.split(" ", 2)[2]
        if markers[rank] is None:
            ok = False
        print(f"--- memo leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    if ok and markers[0] != markers[1]:
        print(f"memo leg: FAIL (rank skew: r0={markers[0]} "
              f"r1={markers[1]})")
        ok = False
    elif ok:
        print(f"memo leg: lockstep across ranks ({markers[0]})")

    # Each per-rank trace must carry memo-served flush spans: the hits
    # were real short-circuits, visible to trace_report's memo line.
    import json

    for rank in range(2):
        path = f"{trace_base}.rank{rank}"
        try:
            with open(path) as f:
                evs = [json.loads(ln) for ln in f if ln.strip()]
            n_memo = sum(1 for e in evs if e.get("type") == "flush"
                         and e.get("cache") == "memo")
            print(f"memo leg rank {rank}: {len(evs)} events, "
                  f"{n_memo} memo-served flushes")
            if n_memo < 3:
                print(f"memo leg rank {rank}: FAIL (memo spans={n_memo})")
                ok = False
        except (OSError, ValueError) as e:
            print(f"memo leg rank {rank}: FAIL ({e})")
            ok = False

    print(f"two-process memo leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_plancache_leg() -> int:
    """Two ranks under RAMBA_PLANCERT=1 + strict verify; the cache
    key/signature are pure functions of rank-identical state, so both
    ranks must store and redeem certificates in LOCKSTEP (a hit skips
    the analysis pipeline — rank-skewed decisions would desync the
    flush sequences), with zero batched-agree divergences."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_plancache_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET",
                  "RAMBA_ARTIFACTS", "RAMBA_VERIFY_RULES"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_PLANCERT"] = "1"
        env["RAMBA_PLANCERT_AGREE"] = "2"
        env["RAMBA_VERIFY"] = "strict"
        env["RAMBA_TRACE"] = trace_base
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _PLANCACHE_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    # Hit/store/stale counts are a deterministic function of the flush
    # sequence over rank-identical state: markers must be IDENTICAL.
    markers = [None, None]
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        for line in tail:
            if line.startswith(f"PLANCACHE_LEG rank={rank} "):
                markers[rank] = line.split(" ", 2)[2]
        if markers[rank] is None:
            ok = False
        print(f"--- plancache leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    if ok and markers[0] != markers[1]:
        print(f"plancache leg: FAIL (rank skew: r0={markers[0]} "
              f"r1={markers[1]})")
        ok = False
    elif ok:
        print(f"plancache leg: lockstep across ranks ({markers[0]})")

    # Each per-rank trace must carry certificate-redeemed flush spans:
    # the hits were real analysis skips, visible to trace_report.
    import json

    for rank in range(2):
        path = f"{trace_base}.rank{rank}"
        try:
            with open(path) as f:
                evs = [json.loads(ln) for ln in f if ln.strip()]
            n_hit = sum(1 for e in evs if e.get("type") == "flush"
                        and e.get("plan_cache") == "hit")
            print(f"plancache leg rank {rank}: {len(evs)} events, "
                  f"{n_hit} certificate-redeemed flushes")
            if n_hit < 3:
                print(f"plancache leg rank {rank}: FAIL "
                      f"(plan_cache spans={n_hit})")
                ok = False
        except (OSError, ValueError) as e:
            print(f"plancache leg rank {rank}: FAIL ({e})")
            ok = False

    print(f"two-process plancache leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_warmstart_leg() -> int:
    """Cold phase + warm phase of two SPMD ranks each, sharing per-rank
    RAMBA_CACHE dirs across phases.  Both ranks must pick IDENTICAL
    compile classes per fingerprint (the decision is pure in program
    structure, shapes, and policy), and the warm phase must hit the
    pre-seeded persist cache in lockstep (equal, nonzero hit counts)."""
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_warmstart_")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))
    ok = True
    # markers[phase][rank] -> {"classes": str, "hits": int, ...}
    markers: dict = {}

    for phase in ("cold", "warm"):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            port = s.getsockname()[1]
        procs, logs = [], []
        for rank in range(2):
            env = dict(os.environ)
            env["PYTHONPATH"] = REPO
            for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                      "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                      "RAMBA_PROFILE_DIR", "RAMBA_FAULTS",
                      "RAMBA_HBM_BUDGET", "RAMBA_MEMO"):
                env.pop(k, None)
            env["JAX_PLATFORMS"] = "cpu"
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
            env["RAMBA_COMPILE_CLASSES"] = "pow2"
            # per-rank cache dir, SHARED across phases: the warm phase
            # reads what its own rank's cold phase stored
            env["RAMBA_CACHE"] = os.path.join(basetemp, f"cache.rank{rank}")
            log = open(os.path.join(basetemp, f"{phase}.rank{rank}.log"),
                       "w")
            logs.append(log)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _WARMSTART_WORKLOAD, str(rank),
                 f"localhost:{port}", phase],
                env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
            ))
        deadline = time.time() + budget
        rcs = [None, None]
        try:
            for i, p in enumerate(procs):
                left = max(5.0, deadline - time.time())
                try:
                    rcs[i] = p.wait(timeout=left)
                except subprocess.TimeoutExpired:
                    p.kill()
                    rcs[i] = -9
        finally:
            for log in logs:
                log.close()
        phase_ok = all(rc == 0 for rc in rcs)
        markers[phase] = [None, None]
        for rank in range(2):
            path = os.path.join(basetemp, f"{phase}.rank{rank}.log")
            with open(path) as f:
                tail = f.read().splitlines()
            prefix = f"WARMSTART_LEG rank={rank} phase={phase} "
            for line in tail:
                if line.startswith(prefix):
                    fields = dict(
                        kv.split("=", 1)
                        for kv in line[len(prefix):].split(" "))
                    markers[phase][rank] = fields
            if markers[phase][rank] is None:
                phase_ok = False
            print(f"--- warmstart {phase} rank {rank} rc={rcs[rank]} "
                  f"({path}) ---")
            print("\n".join(tail[-(3 if phase_ok else 40):]))
        ok = ok and phase_ok
        if not phase_ok:
            break

    if ok:
        for phase in ("cold", "warm"):
            r0, r1 = markers[phase]
            if r0["classes"] != r1["classes"]:
                print(f"warmstart leg: FAIL ({phase} class skew: "
                      f"r0={r0['classes']} r1={r1['classes']})")
                ok = False
        if ok and markers["cold"][0]["classes"] != \
                markers["warm"][0]["classes"]:
            print("warmstart leg: FAIL (classes drifted across phases)")
            ok = False
        if ok:
            h0 = int(markers["warm"][0]["persist_hits"])
            h1 = int(markers["warm"][1]["persist_hits"])
            if h0 != h1 or h0 < 1:
                print(f"warmstart leg: FAIL (persist hits not lockstep: "
                      f"r0={h0} r1={h1})")
                ok = False
            else:
                print(f"warmstart leg: lockstep classes "
                      f"({markers['warm'][0]['classes']}), "
                      f"{h0} persist hits per rank, warm compiles="
                      f"{markers['warm'][0]['compiles']} "
                      f"(cold={markers['cold'][0]['compiles']})")

    print(f"two-process warmstart leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_autotune_leg() -> int:
    """Two ranks under RAMBA_AUTOTUNE=race; both must latch the SAME
    backend per kernel fingerprint (selection is ledger-count-driven and
    counts advance in SPMD lockstep), and each rank's persisted decision
    table must agree with its in-memory decisions."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_autotune_")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS", "RAMBA_HBM_BUDGET"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_AUTOTUNE"] = "race"
        env["RAMBA_AUTOTUNE_K"] = "2"
        env["RAMBA_AUTOTUNE_CACHE"] = os.path.join(
            basetemp, f"autotune.rank{rank}.json")
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _AUTOTUNE_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    decisions = [None, None]
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        for line in tail:
            if line.startswith(f"AUTOTUNE_LEG_DECISIONS rank={rank} "):
                decisions[rank] = line.split(" ", 2)[2]
        if decisions[rank] is None:
            ok = False
        print(f"--- autotune leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    if ok and decisions[0] != decisions[1]:
        print(f"autotune leg: FAIL (backend decisions diverge: "
              f"r0={decisions[0]} r1={decisions[1]})")
        ok = False
    elif ok:
        print(f"autotune leg: decisions identical on both ranks "
              f"({decisions[0]})")

    print(f"two-process autotune leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def run_memory_leg() -> int:
    """Two ranks under a tiny HBM budget; admission control must route
    both to the chunked rung, in lockstep, with the correct result."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_mem_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_FAULTS"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        # Tiny budget: the 65536-elem f32 chain estimates ~768 KB peak,
        # far over a 100 KB budget, so admission must reject pre-flush.
        env["RAMBA_HBM_BUDGET"] = "100k"
        env["RAMBA_HBM_ESTIMATE"] = "analytic"
        env["RAMBA_TRACE"] = trace_base
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _MEMORY_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    # Per-rank traces must show the admission rejection routing to the
    # chunked rung — the memory timeline works under SPMD.
    import json

    for rank in range(2):
        path = f"{trace_base}.rank{rank}"
        try:
            with open(path) as f:
                evs = [json.loads(ln) for ln in f if ln.strip()]
            n_mem = sum(1 for e in evs if e.get("type") == "memory")
            n_reject = sum(1 for e in evs if e.get("type") == "memory"
                           and e.get("action") == "reject")
            print(f"memory leg rank {rank}: {len(evs)} events, "
                  f"{n_mem} memory, {n_reject} rejects")
            if n_mem == 0 or n_reject == 0:
                print(f"memory leg rank {rank}: FAIL "
                      f"(memory={n_mem}, reject={n_reject})")
                ok = False
        except (OSError, ValueError) as e:
            print(f"memory leg rank {rank}: FAIL ({e})")
            ok = False

    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        if "MEMORY_LEG_OK rank=%d" % rank not in "\n".join(tail):
            ok = False
        print(f"--- memory leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    print(f"two-process memory leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


# SPMD workload for the chaos leg: ~two dozen elementwise flush+gather
# iterations under rank-1-only fault injection.  Elementwise programs
# keep the degradation ladder communication-free (no collective inside a
# rung can wedge the healthy rank mid-attempt); the only collectives are
# the coherence agreement rounds and the post-flush all-gather — so with
# coherence ON a terminal failure anywhere makes BOTH ranks skip the
# gather together, and with coherence OFF the skew mispairs the gathers,
# which is exactly the historical failure mode.  Iteration FATAL_AT
# swaps in a one-shot fatal injection (coherent quarantine everywhere);
# errors are printed by their *agreed classification* (retry.classify),
# which is the cross-rank-comparable name for a failure.
# argv: <rank> <coordinator>.
_CHAOS_WORKLOAD = """
import hashlib
import os
import sys
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu.resilience import faults, retry

N = 4096
ITERS = 24
FATAL_AT = 18
base_spec = os.environ.get('RAMBA_FAULTS')
for i in range(ITERS):
    if i == FATAL_AT:
        faults.configure('execute:1:fatal:rank=1')
    elif i == FATAL_AT + 1:
        faults.configure(base_spec)
    try:
        a = (rt.arange(N) + float(i)) * 2.0 + 1.0
        b = a * a - 3.0 * a
        v = b.asarray()
        ref = (np.arange(N) + float(i)) * 2.0 + 1.0
        ref = ref * ref - 3.0 * ref
        good = 'ok' if np.allclose(v, ref, rtol=1e-5) else 'BAD'
        line = 'i=%02d sha=%s %s' % (
            i, hashlib.sha256(v.tobytes()).hexdigest()[:16], good)
        del a, b, v
    except Exception as e:
        line = 'i=%02d err=%s' % (i, retry.classify(e))
    print('CHAOS_RESULT ' + line, flush=True)
print('CHAOS_DONE rank=%d' % rank, flush=True)
"""


def _chaos_env(basetemp: str, trace_base: str, coherence: str) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
              "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
              "RAMBA_PROFILE_DIR"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # Every fault targets rank 1 only — the skew the protocol must absorb.
    env["RAMBA_FAULTS"] = ("dispatch:0.25:rank=1,execute:0.15:rank=1,"
                           "oom:0.1:rank=1:bytes=1m")
    env["RAMBA_FAULTS_SEED"] = "1234"
    env["RAMBA_RETRY_BASE_S"] = "0.01"
    env["RAMBA_WATCHDOG_S"] = "45"  # tripwire: ON phase must never trip it
    env["RAMBA_COHERENCE"] = coherence
    env["RAMBA_TRACE"] = trace_base
    return env


def _chaos_run(basetemp: str, trace_base: str, coherence: str,
               budget: float, grace: float = 30.0):
    """Launch both ranks, wait with a straggler grace window (once one
    rank exits, the other gets ``grace`` seconds before the kill — the
    OFF phase intentionally wedges a rank and must not eat the full
    budget).  Returns per-rank return codes (-9 = killed)."""
    procs, logs = [], []
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    for rank in range(2):
        env = _chaos_env(basetemp, trace_base, coherence)
        log = open(os.path.join(basetemp, f"{coherence}.rank{rank}.log"),
                   "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHAOS_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))
    deadline = time.time() + budget
    shrunk = False
    rcs = [None, None]
    try:
        while any(rc is None for rc in rcs) and time.time() < deadline:
            for i, p in enumerate(procs):
                if rcs[i] is None and p.poll() is not None:
                    rcs[i] = p.returncode
            if not shrunk and sum(rc is not None for rc in rcs) == 1:
                deadline = min(deadline, time.time() + grace)
                shrunk = True
            time.sleep(0.25)
        for i, p in enumerate(procs):
            if rcs[i] is None:
                p.kill()
                p.wait()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()
    return rcs


def _chaos_events(trace_base: str, rank: int) -> list:
    import json

    path = f"{trace_base}.rank{rank}"
    try:
        with open(path) as f:
            return [json.loads(ln) for ln in f if ln.strip()]
    except (OSError, ValueError):
        return []


def _chaos_results(basetemp: str, coherence: str, rank: int) -> list:
    path = os.path.join(basetemp, f"{coherence}.rank{rank}.log")
    try:
        with open(path) as f:
            return [ln.strip() for ln in f
                    if ln.startswith("CHAOS_RESULT ")]
    except OSError:
        return []


def run_chaos_leg() -> int:
    """Rank-skewed chaos soak: coherence ON must hold the fleet in
    lockstep; coherence OFF (same seed) must reproduce the historical
    divergence failure mode."""
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_chaos_")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))
    ok = True

    # ---- phase ON: the protocol absorbs the skew -----------------------
    trace_on = os.path.join(basetemp, "trace_on.jsonl")
    rcs = _chaos_run(basetemp, trace_on, "on", budget)
    if rcs != [0, 0]:
        print(f"chaos leg ON: FAIL (rcs={rcs}, expected clean exits)")
        ok = False
    res = [_chaos_results(basetemp, "on", r) for r in range(2)]
    if not res[0] or res[0] != res[1]:
        print(f"chaos leg ON: FAIL (per-iteration results diverge: "
              f"rank0={len(res[0])} lines, rank1={len(res[1])} lines)")
        for l0, l1 in zip(res[0], res[1]):
            if l0 != l1:
                print(f"  rank0: {l0}\n  rank1: {l1}")
        ok = False
    if any("BAD" in ln for ln in res[0] + res[1]):
        print("chaos leg ON: FAIL (numerically wrong result)")
        ok = False
    evs = [_chaos_events(trace_on, r) for r in range(2)]
    coh_seq = [[(e.get("site"), e.get("epoch"), e.get("decision"))
                for e in evs[r] if e.get("type") == "coherence"]
               for r in range(2)]
    rung_seq = [[(e.get("site"), e.get("from"), e.get("to"))
                 for e in evs[r] if e.get("type") == "degrade"
                 and e.get("action") == "rung"] for r in range(2)]
    retry_seq = [[(e.get("site"), e.get("action"), e.get("attempt"))
                  for e in evs[r] if e.get("type") == "degrade"
                  and e.get("action") in ("retry", "exhausted")]
                 for r in range(2)]
    quar = [[e for e in evs[r] if e.get("type") == "flush_error"]
            for r in range(2)]
    stalls = [sum(1 for e in evs[r] if e.get("type") == "stall")
              for r in range(2)]
    local_rounds = [sum(1 for e in evs[r] if e.get("type") == "coherence"
                        and e.get("outcome") == "local") for r in range(2)]
    faults_fired = [sum(1 for e in evs[r] if e.get("type") == "fault")
                    for r in range(2)]
    overrides = sum(1 for e in evs[0] if e.get("type") == "coherence"
                    and e.get("decision") != e.get("proposal"))
    print(f"chaos leg ON: {len(coh_seq[0])}/{len(coh_seq[1])} coherence "
          f"rounds, {len(rung_seq[0])}/{len(rung_seq[1])} rung drops, "
          f"{len(retry_seq[0])}/{len(retry_seq[1])} retries, "
          f"{len(quar[0])}/{len(quar[1])} quarantines, "
          f"faults r0/r1={faults_fired[0]}/{faults_fired[1]}, "
          f"rank0 dragged {overrides}x")
    for name, seq in (("coherence", coh_seq), ("rung", rung_seq),
                      ("retry", retry_seq)):
        if not seq[0] or seq[0] != seq[1]:
            print(f"chaos leg ON: FAIL ({name} decision sequences differ "
                  f"or empty: {len(seq[0])} vs {len(seq[1])})")
            ok = False
    if len(quar[0]) != len(quar[1]) or not quar[0]:
        print(f"chaos leg ON: FAIL (quarantines {len(quar[0])} vs "
              f"{len(quar[1])}, expected equal and >= 1)")
        ok = False
    elif not all(e.get("coherence_epoch") for e in quar[0] + quar[1]):
        print("chaos leg ON: FAIL (quarantine missing coherence_epoch)")
        ok = False
    if stalls != [0, 0]:
        print(f"chaos leg ON: FAIL (stall events {stalls}, expected zero)")
        ok = False
    if local_rounds != [0, 0]:
        print(f"chaos leg ON: FAIL (local-fallback rounds {local_rounds})")
        ok = False
    if faults_fired[0] != 0 or faults_fired[1] == 0:
        print(f"chaos leg ON: FAIL (fault skew wrong: {faults_fired})")
        ok = False
    if overrides == 0:
        print("chaos leg ON: FAIL (rank 0 never overridden — the soak "
              "exercised no skew)")
        ok = False

    # ---- phase OFF: same seed, no protocol → divergence comes back -----
    trace_off = os.path.join(basetemp, "trace_off.jsonl")
    off_rcs = _chaos_run(basetemp, trace_off, "off",
                         min(budget, 150.0), grace=20.0)
    off_res = [_chaos_results(basetemp, "off", r) for r in range(2)]
    off_evs = [_chaos_events(trace_off, r) for r in range(2)]
    off_rungs = [[(e.get("site"), e.get("from"), e.get("to"))
                  for e in off_evs[r] if e.get("type") == "degrade"
                  and e.get("action") == "rung"] for r in range(2)]
    off_stalls = sum(1 for r in range(2) for e in off_evs[r]
                     if e.get("type") == "stall")
    diverged = (off_rcs != [0, 0] or off_res[0] != off_res[1]
                or off_rungs[0] != off_rungs[1] or off_stalls > 0)
    print(f"chaos leg OFF: rcs={off_rcs}, result lines "
          f"{len(off_res[0])}/{len(off_res[1])} "
          f"(identical={off_res[0] == off_res[1]}), rung drops "
          f"{len(off_rungs[0])}/{len(off_rungs[1])}, stalls={off_stalls}")
    if not diverged:
        print("chaos leg OFF: FAIL (coherence off did NOT reproduce the "
              "divergence — the ON-phase result proves nothing)")
        ok = False
    else:
        print("chaos leg OFF: divergence reproduced (expected)")

    print(f"two-process chaos leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    else:
        print(f"chaos leg artifacts kept at {basetemp}")
    return 0 if ok else 1


def _overload_env(trace_base: str, coherence: str) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO
    for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
              "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
              "RAMBA_PROFILE_DIR"):
        env.pop(k, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    # Rank 1 alone proposes shedding the first three flushes; the
    # serve:shed agreement must make that the fleet-wide verdict.
    env["RAMBA_FAULTS"] = "serve:admit:3:rank=1"
    env["RAMBA_RETRY_BASE_S"] = "0.01"
    env["RAMBA_WATCHDOG_S"] = "45"
    env["RAMBA_COHERENCE"] = coherence
    env["RAMBA_TRACE"] = trace_base
    return env


def _overload_run(basetemp: str, trace_base: str, coherence: str,
                  budget: float, grace: float = 30.0):
    """Launch both ranks with a straggler grace window (the OFF phase
    intentionally splits the fleet and may wedge one rank on a
    mismatched collective)."""
    procs, logs = [], []
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    for rank in range(2):
        log = open(os.path.join(basetemp,
                                f"{coherence}.rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _OVERLOAD_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=_overload_env(trace_base, coherence),
            stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))
    deadline = time.time() + budget
    shrunk = False
    rcs = [None, None]
    try:
        while any(rc is None for rc in rcs) and time.time() < deadline:
            for i, p in enumerate(procs):
                if rcs[i] is None and p.poll() is not None:
                    rcs[i] = p.returncode
            if not shrunk and sum(rc is not None for rc in rcs) == 1:
                deadline = min(deadline, time.time() + grace)
                shrunk = True
            time.sleep(0.25)
        for i, p in enumerate(procs):
            if rcs[i] is None:
                p.kill()
                p.wait()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()
    return rcs


def _overload_markers(basetemp: str, coherence: str, rank: int) -> list:
    path = os.path.join(basetemp, f"{coherence}.rank{rank}.log")
    try:
        with open(path) as f:
            return [ln.strip() for ln in f
                    if ln.startswith(("OVERLOAD_RESULT ", "OVERLOAD_HEAL ",
                                      "OVERLOAD_COUNTS "))]
    except OSError:
        return []


def run_overload_leg() -> int:
    """Coherent load shedding under rank-skewed admission faults: ON
    sheds byte-identically on every rank (same set, same epoch, zero
    stalls, zero local fallbacks); OFF reproduces the divergence."""
    import json

    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_overload_")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))
    ok = True

    # ---- phase ON: the shed verdict is epoch-agreed --------------------
    trace_on = os.path.join(basetemp, "trace_on.jsonl")
    rcs = _overload_run(basetemp, trace_on, "on", budget)
    if rcs != [0, 0]:
        print(f"overload leg ON: FAIL (rcs={rcs}, expected clean exits)")
        ok = False
    marks = [_overload_markers(basetemp, "on", r) for r in range(2)]
    sheds = [[ln for ln in marks[r] if "verdict=SHED" in ln]
             for r in range(2)]
    print(f"overload leg ON: markers {len(marks[0])}/{len(marks[1])}, "
          f"sheds {len(sheds[0])}/{len(sheds[1])}")
    if not marks[0] or marks[0] != marks[1]:
        print("overload leg ON: FAIL (marker lines diverge across ranks)")
        for l0, l1 in zip(marks[0], marks[1]):
            if l0 != l1:
                print(f"  rank0: {l0}\n  rank1: {l1}")
        ok = False
    if len(sheds[0]) != 3 or any("epoch=None" in ln for ln in sheds[0]):
        print(f"overload leg ON: FAIL (expected 3 epoch-stamped sheds, "
              f"got {sheds[0]})")
        ok = False
    if any("BAD" in ln for ln in marks[0] + marks[1]):
        print("overload leg ON: FAIL (shed array healed to wrong bytes)")
        ok = False
    for rank in range(2):
        path = f"{trace_on}.rank{rank}"
        try:
            with open(path) as f:
                evs = [json.loads(ln) for ln in f if ln.strip()]
        except (OSError, ValueError) as e:
            print(f"overload leg ON: FAIL (trace rank {rank}: {e})")
            ok = False
            continue
        stalls = sum(1 for e in evs if e.get("type") == "stall")
        local = sum(1 for e in evs if e.get("type") == "coherence"
                    and e.get("outcome") == "local")
        shed_evs = [e for e in evs if e.get("type") == "shed"
                    and e.get("stage") == "dispatch"]
        if stalls or local:
            print(f"overload leg ON: FAIL (rank {rank}: {stalls} stalls, "
                  f"{local} local coherence rounds — agreement broke)")
            ok = False
        if len(shed_evs) != 3 or any(not e.get("epoch")
                                     for e in shed_evs):
            print(f"overload leg ON: FAIL (rank {rank}: shed trace events "
                  f"{len(shed_evs)}, expected 3 epoch-stamped)")
            ok = False

    # ---- phase OFF: same seed, no agreement → rank 1 sheds alone -------
    trace_off = os.path.join(basetemp, "trace_off.jsonl")
    off_rcs = _overload_run(basetemp, trace_off, "off",
                            min(budget, 150.0), grace=20.0)
    off_marks = [_overload_markers(basetemp, "off", r) for r in range(2)]
    diverged = off_rcs != [0, 0] or off_marks[0] != off_marks[1]
    print(f"overload leg OFF: rcs={off_rcs}, markers "
          f"{len(off_marks[0])}/{len(off_marks[1])} "
          f"(identical={off_marks[0] == off_marks[1]})")
    if not diverged:
        print("overload leg OFF: FAIL (coherence off did NOT reproduce "
              "the shed divergence — the ON result proves nothing)")
        ok = False
    else:
        print("overload leg OFF: divergence reproduced (expected)")

    print(f"two-process overload leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    else:
        print(f"overload leg artifacts kept at {basetemp}")
    return 0 if ok else 1


def run_fault_leg() -> int:
    """Two ranks, one injected compile fault each; both must recover."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_fault_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))

    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_FAULTS"] = "compile:once"
        env["RAMBA_RETRY_BASE_S"] = "0.01"
        env["RAMBA_TRACE"] = trace_base
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _FAULT_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    # Per-rank traces must show the injected fault AND the retry that
    # absorbed it — the degradation timeline works under SPMD.
    import json

    for rank in range(2):
        path = f"{trace_base}.rank{rank}"
        try:
            with open(path) as f:
                evs = [json.loads(ln) for ln in f if ln.strip()]
            n_fault = sum(1 for e in evs if e.get("type") == "fault"
                          and e.get("site") == "compile")
            n_retry = sum(1 for e in evs if e.get("type") == "degrade"
                          and e.get("action") == "retry")
            print(f"fault leg rank {rank}: {len(evs)} events, "
                  f"{n_fault} faults, {n_retry} retries")
            if n_fault == 0 or n_retry == 0:
                print(f"fault leg rank {rank}: FAIL "
                      f"(fault={n_fault}, retry={n_retry})")
                ok = False
        except (OSError, ValueError) as e:
            print(f"fault leg rank {rank}: FAIL ({e})")
            ok = False

    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        if "FAULT_LEG_OK rank=%d" % rank not in "\n".join(tail):
            ok = False
        print(f"--- fault leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    print(f"two-process fault leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1




# SPMD workload for the integrity leg's ON phase: both ranks flush
# three distinct effect-certified pure programs under RAMBA_AUDIT=1 so
# every flush is shadow-audited.  The harness arms
# RAMBA_FAULTS='audit:shadow:flip:bytes=1:rank=1:after=1' — exactly one
# audit, on rank 1 only, sees flipped shadow bytes.  The verdict is
# agreed via coherence.agree(reduce="max"), so BOTH ranks must count
# the same single mismatch, suppress the same memo insert, and still
# serve the correct primary values.  argv: <rank> <coordinator>.
_INTEGRITY_WORKLOAD = """
import sys
import numpy as np
rank, coord = int(sys.argv[1]), sys.argv[2]
from ramba_tpu.parallel import distributed
distributed.initialize(coordinator_address=coord, num_processes=2,
                       process_id=rank)
import jax
assert jax.process_count() == 2, jax.process_count()
import ramba_tpu as rt
from ramba_tpu.core import memo
from ramba_tpu.resilience import integrity
assert memo.enabled(), 'RAMBA_MEMO not armed'
assert integrity.audit_every() == 1, 'RAMBA_AUDIT not armed'
a = rt.arange(4096) / 100.0
b = rt.arange(4096) * 0.5 + 1.0
rt.sync()
vals = [float(rt.sum((a + b) * k)) for k in (2.0, 3.0, 4.0)]
an = np.arange(4096)
base = an / 100.0 + (an * 0.5 + 1.0)
for k, v in zip((2.0, 3.0, 4.0), vals):
    exp = float(np.sum(base * k))
    assert abs(v - exp) <= 1e-4 * abs(exp), (k, v, exp)
snap = integrity.snapshot()
assert snap['audits'] >= 3, snap
assert snap['audit_mismatches'] == 1, snap
assert snap['audit_errors'] == 0, snap
msnap = memo.cache.snapshot()
print('INTEGRITY_LEG rank=%d audits=%d mismatches=%d inserts=%d '
      'checksum=%.6f' % (rank, snap['audits'], snap['audit_mismatches'],
                         msnap['inserts'], sum(vals)))
"""


# Single-process workloads for the integrity leg's OFF phase.  Seed:
# flush one memoizable program with the shared artifact tier armed so a
# stamped memo blob lands on disk; print the correct value and the blob
# path.  Probe: a fresh process recomputes the same program — the
# shared lane is keyed by content, so it adopts whatever the blob
# holds.  Between seed and probe the harness replaces the blob with a
# VALID but WRONG unstamped npz: with RAMBA_INTEGRITY=0 the probe
# serves the wrong answer verbatim (the failure mode this plane
# exists to stop); with the plane on the unstamped blob is evicted and
# the recompute serves the correct answer.
_INTEGRITY_SEED_WORKLOAD = """
import os
import numpy as np
import ramba_tpu as rt
from ramba_tpu.core import memo
from ramba_tpu.fleet import artifacts
assert memo.enabled() and artifacts.memo_shared_enabled()
x = rt.fromarray(np.arange(256) * 0.5)
v = float(rt.sum(x * 3.0 + 1.0))
memo_dir = os.path.join(os.environ['RAMBA_ARTIFACTS'], 'memo')
blobs = sorted(n for n in os.listdir(memo_dir) if n.endswith('.npz'))
assert len(blobs) == 1, blobs
print('INTEGRITY_SEED value=%.6f blob=%s' % (v, blobs[0]))
"""

_INTEGRITY_PROBE_WORKLOAD = """
import numpy as np
import ramba_tpu as rt
from ramba_tpu.core import memo
from ramba_tpu.fleet import artifacts
from ramba_tpu.resilience import integrity
x = rt.fromarray(np.arange(256) * 0.5)
v = float(rt.sum(x * 3.0 + 1.0))
snap = artifacts.snapshot()
print('INTEGRITY_PROBE value=%.6f shared_hits=%d corrupt=%d '
      'failures=%d' % (v, snap['memo_hits'], snap['memo_corrupt'],
                       integrity.stats['failures']))
"""


def run_integrity_leg() -> int:
    """Two phases: (ON) 2-rank coherent shadow-audit verdict under a
    seeded rank-1 shadow flip; (OFF) the wrong-answer serve reproduced
    with RAMBA_INTEGRITY=0 and caught with the plane on."""
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_integrity_")
    trace_base = os.path.join(basetemp, "trace.jsonl")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "600"))
    ok = True

    # -- ON phase: coherent audit verdict across ranks -------------------
    procs, logs = [], []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_PROFILE_DIR", "RAMBA_HBM_BUDGET",
                  "RAMBA_MEMO_BUDGET", "RAMBA_ARTIFACTS",
                  "RAMBA_INTEGRITY"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_MEMO"] = "1"
        env["RAMBA_AUDIT"] = "1"
        env["RAMBA_FAULTS"] = "audit:shadow:flip:bytes=1:rank=1:after=1"
        env["RAMBA_TRACE"] = trace_base
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _INTEGRITY_WORKLOAD, str(rank),
             f"localhost:{port}"],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))
    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()
    ok = all(rc == 0 for rc in rcs)

    markers = [None, None]
    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()
        for line in tail:
            if line.startswith(f"INTEGRITY_LEG rank={rank} "):
                markers[rank] = line.split(" ", 2)[2]
        if markers[rank] is None:
            ok = False
        print(f"--- integrity leg rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail[-(4 if ok else 40):]))
    if ok and markers[0] != markers[1]:
        print(f"integrity leg: FAIL (rank skew: r0={markers[0]} "
              f"r1={markers[1]})")
        ok = False
    elif ok:
        print(f"integrity leg ON: agreed verdict across ranks "
              f"({markers[0]})")

    # The agreed mismatch must be visible as an ``integrity`` trace
    # event on BOTH ranks (rank 0 had no local mismatch — the event is
    # the coherently-agreed one).
    import json

    for rank in range(2):
        path = f"{trace_base}.rank{rank}"
        try:
            with open(path) as f:
                evs = [json.loads(ln) for ln in f if ln.strip()]
            n_int = sum(1 for e in evs if e.get("type") == "integrity"
                        and e.get("site") == "audit:shadow")
            print(f"integrity leg rank {rank}: {len(evs)} events, "
                  f"{n_int} integrity events")
            if n_int < 1:
                ok = False
        except (OSError, ValueError) as e:
            print(f"integrity leg rank {rank}: FAIL ({e})")
            ok = False

    # -- OFF phase: the wrong-answer serve, reproduced then caught -------
    art = os.path.join(basetemp, "artifacts")
    os.makedirs(art, exist_ok=True)

    def run_single(workload, *, integrity_on):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        for k in ("RAMBA_TEST_PROCS", "RAMBA_TEST_PROC_ID",
                  "RAMBA_TEST_COORD", "RAMBA_TEST_SHARED_TMP",
                  "RAMBA_FAULTS", "RAMBA_TRACE", "RAMBA_AUDIT"):
            env.pop(k, None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["RAMBA_MEMO"] = "1"
        env["RAMBA_ARTIFACTS"] = art
        env["RAMBA_INTEGRITY"] = "1" if integrity_on else "0"
        return subprocess.run(
            [sys.executable, "-c", workload], env=env, cwd=REPO,
            capture_output=True, text=True, timeout=budget)

    r = run_single(_INTEGRITY_SEED_WORKLOAD, integrity_on=True)
    seed_val, blob = None, None
    for line in r.stdout.splitlines():
        if line.startswith("INTEGRITY_SEED "):
            fields = dict(f.split("=", 1) for f in line.split()[1:])
            seed_val = float(fields["value"])
            blob = os.path.join(art, "memo", fields["blob"])
    if r.returncode != 0 or blob is None:
        print(f"integrity leg OFF: seed FAILED rc={r.returncode}\n"
              f"{r.stdout[-2000:]}{r.stderr[-2000:]}")
        ok = False
    else:
        # Clobber: a VALID npz of wrong values, UNSTAMPED — the shape a
        # pre-plane cache poisoning takes.  (A bit flip inside the npz
        # usually trips zipfile's CRC; this is the flip that parses.)
        import io

        import numpy as np

        wrong = np.full(1, -12345.0)
        buf = io.BytesIO()
        np.savez(buf, out0=wrong)
        with open(blob, "wb") as f:
            f.write(buf.getvalue())

        r_off = run_single(_INTEGRITY_PROBE_WORKLOAD, integrity_on=False)
        r_on = run_single(_INTEGRITY_PROBE_WORKLOAD, integrity_on=True)

        def probe_fields(r):
            for line in r.stdout.splitlines():
                if line.startswith("INTEGRITY_PROBE "):
                    return dict(f.split("=", 1)
                                for f in line.split()[1:])
            return None

        f_off, f_on = probe_fields(r_off), probe_fields(r_on)
        if r_off.returncode != 0 or f_off is None:
            print(f"integrity leg OFF: probe FAILED rc={r_off.returncode}"
                  f"\n{r_off.stdout[-2000:]}{r_off.stderr[-2000:]}")
            ok = False
        elif not (float(f_off["value"]) == -12345.0
                  and int(f_off["shared_hits"]) >= 1):
            print(f"integrity leg OFF: wrong-answer serve NOT reproduced "
                  f"({f_off} vs seed {seed_val})")
            ok = False
        else:
            print(f"integrity leg OFF: RAMBA_INTEGRITY=0 served the "
                  f"poisoned value {f_off['value']} (seed {seed_val:g})")
        if r_on.returncode != 0 or f_on is None:
            print(f"integrity leg ON: probe FAILED rc={r_on.returncode}"
                  f"\n{r_on.stdout[-2000:]}{r_on.stderr[-2000:]}")
            ok = False
        elif not (abs(float(f_on["value"]) - seed_val) <= 1e-6
                  and int(f_on["corrupt"]) >= 1
                  and int(f_on["failures"]) >= 1):
            print(f"integrity leg ON: poisoned blob not caught ({f_on})")
            ok = False
        else:
            print(f"integrity leg ON: unstamped blob evicted "
                  f"(corrupt={f_on['corrupt']}), recomputed correct "
                  f"value {f_on['value']}")

    print(f"two-process integrity leg: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


def main() -> int:
    if "--fault-leg" in sys.argv[1:]:
        return run_fault_leg()
    if "--chaos-leg" in sys.argv[1:]:
        return run_chaos_leg()
    if "--memory-leg" in sys.argv[1:]:
        return run_memory_leg()
    if "--perf-leg" in sys.argv[1:]:
        return run_perf_leg()
    if "--attrib-leg" in sys.argv[1:]:
        return run_attrib_leg()
    if "--serving-leg" in sys.argv[1:]:
        return run_serving_leg()
    if "--elastic-leg" in sys.argv[1:]:
        return run_elastic_leg()
    if "--reshard-leg" in sys.argv[1:]:
        return run_reshard_leg()
    if "--telemetry-leg" in sys.argv[1:]:
        return run_telemetry_leg()
    if "--fleet-leg" in sys.argv[1:]:
        return run_fleet_leg()
    if "--router-leg" in sys.argv[1:]:
        return run_router_leg()
    if "--autotune-leg" in sys.argv[1:]:
        return run_autotune_leg()
    if "--integrity-leg" in sys.argv[1:]:
        return run_integrity_leg()
    if "--memo-leg" in sys.argv[1:]:
        return run_memo_leg()
    if "--plancache-leg" in sys.argv[1:]:
        return run_plancache_leg()
    if "--warmstart-leg" in sys.argv[1:]:
        return run_warmstart_leg()
    if "--overload-leg" in sys.argv[1:]:
        return run_overload_leg()
    if "--sampling-leg" in sys.argv[1:]:
        return run_sampling_leg()
    pytest_args = sys.argv[1:] or ["tests/"]
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    basetemp = tempfile.mkdtemp(prefix="ramba_2proc_")
    budget = float(os.environ.get("RAMBA_TEST_PROCS_TIMEOUT", "2400"))

    # Trace leg: both ranks stream flush spans; multi-controller emit
    # writes per-rank files <path>.rank0 / <path>.rank1 (observe/events.py)
    # which are asserted parseable below.
    trace_base = os.path.join(basetemp, "trace.jsonl")

    procs = []
    logs = []
    for rank in range(2):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO  # drop site hooks that force a TPU backend
        env.pop("JAX_PLATFORMS", None)
        env.pop("XLA_FLAGS", None)
        env["RAMBA_TEST_PROCS"] = "2"
        env["RAMBA_TEST_PROC_ID"] = str(rank)
        env["RAMBA_TEST_COORD"] = f"localhost:{port}"
        env["RAMBA_TEST_SHARED_TMP"] = os.path.join(basetemp, "shared")
        env["RAMBA_TRACE"] = trace_base
        log = open(os.path.join(basetemp, f"rank{rank}.log"), "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
             f"--basetemp={os.path.join(basetemp, 'tmp')}", *pytest_args],
            env=env, stdout=log, stderr=subprocess.STDOUT, cwd=REPO,
        ))

    deadline = time.time() + budget
    rcs = [None, None]
    try:
        for i, p in enumerate(procs):
            left = max(5.0, deadline - time.time())
            try:
                rcs[i] = p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                p.kill()
                rcs[i] = -9
    finally:
        for log in logs:
            log.close()

    ok = all(rc == 0 for rc in rcs)

    # Both ranks must have produced a parseable JSONL trace with at least
    # one flush span — the observability stream works under SPMD.
    import json

    for rank in range(2):
        path = f"{trace_base}.rank{rank}"
        try:
            with open(path) as f:
                evs = [json.loads(ln) for ln in f if ln.strip()]
            n_flush = sum(1 for e in evs if e.get("type") == "flush")
            bad_rank = sum(1 for e in evs if e.get("rank") != rank)
            print(f"trace rank {rank}: {len(evs)} events, "
                  f"{n_flush} flush spans")
            if n_flush == 0 or bad_rank:
                print(f"trace rank {rank}: FAIL "
                      f"(flush={n_flush}, mis-ranked={bad_rank})")
                ok = False
        except (OSError, ValueError) as e:
            print(f"trace rank {rank}: FAIL ({e})")
            ok = False

    for rank in range(2):
        path = os.path.join(basetemp, f"rank{rank}.log")
        with open(path) as f:
            tail = f.read().splitlines()[-(4 if ok else 40):]
        print(f"--- rank {rank} rc={rcs[rank]} ({path}) ---")
        print("\n".join(tail))
    print(f"two-process suite: {'OK' if ok else 'FAIL'}")
    if ok:
        shutil.rmtree(basetemp, ignore_errors=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
