#!/usr/bin/env python
"""Offline roofline analysis of a perf capture (`ramba-roofline`).

Takes one capture — ``RAMBA_PERF=1 python bench.py`` stdout, a
``diagnostics.dump()`` snapshot, or a raw ``perf_report()`` dump — and
reports, per compiled kernel, how close it ran to the hardware's peak
and which ceiling (HBM bandwidth or compute) it sits under::

    RAMBA_PERF=sync python bench.py > new.json
    python scripts/roofline_report.py new.json
    python scripts/roofline_report.py new.json --peaks peaks.json --json

Device time per kernel prefers the capture's synchronized window
(``sync`` p50, RAMBA_PERF=sync) and falls back to dispatch-time p50 —
flagged ``dispatch`` in the output, an upper bound on device time under
async dispatch.  The peak table resolves, in order: ``--peaks`` (inline
JSON or a file path), the peak table recorded in the capture itself
(bench.py stamps ``peaks`` + ``device_kind``), then the builtin
per-device_kind table in ramba_tpu/observe/attrib.py.

Exit status: 0 report printed; 2 usage/input error (no kernels, no
flops/bytes — run the capture with RAMBA_PERF=1).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ramba_tpu.observe import attrib  # noqa: E402
from scripts.perf_diff import load_capture  # noqa: E402


def _capture_extras(path: str) -> dict:
    """device_kind / peaks recorded in the capture (bench.py stamps
    them); empty when absent."""
    try:
        with open(path) as f:
            text = f.read()
        try:
            obj = json.loads(text)
        except json.JSONDecodeError:
            obj = None
            for line in reversed(text.splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        obj = json.loads(line)
                        break
                    except json.JSONDecodeError:
                        continue
        if not isinstance(obj, dict):
            return {}
        return {k: obj[k] for k in ("device_kind", "peaks") if k in obj}
    except OSError:
        return {}


def _resolve_peaks(args_peaks, extras: dict) -> dict:
    if args_peaks:
        text = args_peaks
        if not args_peaks.lstrip().startswith("{"):
            with open(args_peaks) as f:
                text = f.read()
        obj = json.loads(text)
        if not isinstance(obj, dict):
            raise ValueError("--peaks must be a JSON object")
        # either a bare {"peak_gbps", "peak_tflops"} entry or a
        # per-device_kind table like RAMBA_PEAKS_JSON
        if "peak_gbps" in obj or "peak_tflops" in obj:
            return {"peak_gbps": float(obj.get("peak_gbps") or 0.0),
                    "peak_tflops": float(obj.get("peak_tflops") or 0.0),
                    "source": "--peaks",
                    "device_kind": extras.get("device_kind")}
        kind = extras.get("device_kind")
        low = (kind or "").lower()
        for key, entry in obj.items():
            if key != "default" and key.lower() in low:
                return {"peak_gbps": float(entry.get("peak_gbps") or 0.0),
                        "peak_tflops": float(entry.get("peak_tflops") or 0.0),
                        "source": f"--peaks:{key}",
                        "device_kind": kind}
        entry = obj.get("default", {})
        return {"peak_gbps": float(entry.get("peak_gbps") or 0.0),
                "peak_tflops": float(entry.get("peak_tflops") or 0.0),
                "source": "--peaks:default", "device_kind": kind}
    rec = extras.get("peaks")
    if isinstance(rec, dict) and (rec.get("peak_gbps")
                                  or rec.get("peak_tflops")):
        return {"peak_gbps": float(rec.get("peak_gbps") or 0.0),
                "peak_tflops": float(rec.get("peak_tflops") or 0.0),
                "source": "capture", "device_kind": extras.get("device_kind")}
    return attrib.peak_table(extras.get("device_kind"))


def _device_seconds(entry: dict) -> tuple:
    """(seconds, source) for one capture kernel entry."""
    sync = (entry.get("sync") or {}).get("p50_s")
    if sync:
        return float(sync), "sync"
    ex = entry.get("exec") or {}
    p50 = ex.get("p50_s")
    if p50:
        return float(p50), "dispatch"
    count, total = ex.get("count"), ex.get("total_s")
    if count and total:
        return float(total) / int(count), "dispatch"
    return 0.0, "none"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-kernel roofline report from a perf capture"
    )
    ap.add_argument("capture", help="bench JSON / perf dump")
    ap.add_argument("--peaks", help="peak table override: inline JSON or "
                    "a file path (bare entry or per-device_kind table)")
    ap.add_argument("--top", type=int, default=20,
                    help="show at most N kernels (default 20)")
    ap.add_argument("--json", action="store_true",
                    help="emit the report as one JSON object")
    args = ap.parse_args(argv)
    try:
        cap = load_capture(args.capture)
        extras = _capture_extras(args.capture)
        peaks = _resolve_peaks(args.peaks, extras)
    except (OSError, ValueError) as e:
        print(f"roofline_report: {e}", file=sys.stderr)
        return 2
    rows = []
    skipped = 0
    for fp, k in cap["kernels"].items():
        flops = float(k.get("flops") or 0.0)
        by = float(k.get("bytes_accessed") or 0.0)
        dev_s, src = _device_seconds(k)
        row = attrib.classify(flops, by, dev_s, peaks)
        if row is None:
            skipped += 1
            continue
        row["fingerprint"] = fp
        row["label"] = k.get("label", "?")
        row["device_p50_s"] = round(dev_s, 6)
        row["device_time_source"] = src
        rows.append(row)
    if not rows:
        print(f"roofline_report: {args.capture}: no kernel has "
              "flops/bytes + a time window (run with RAMBA_PERF=1, "
              "ideally RAMBA_PERF=sync)", file=sys.stderr)
        return 2
    rows.sort(key=lambda r: r["frac_of_peak"], reverse=True)
    shown = rows[:args.top]
    if args.json:
        print(json.dumps({
            "capture": args.capture,
            "device_kind": peaks.get("device_kind"),
            "peaks": {"peak_gbps": peaks["peak_gbps"],
                      "peak_tflops": peaks["peak_tflops"],
                      "source": peaks["source"]},
            "kernels": shown,
            "skipped": skipped,
        }, indent=1))
        return 0
    print(f"roofline_report: {args.capture}: "
          f"device_kind={peaks.get('device_kind') or '?'} "
          f"peaks={peaks['peak_gbps']:g} GB/s / "
          f"{peaks['peak_tflops']:g} TFLOPs ({peaks['source']})")
    print(f"  {len(rows)} kernel(s), {skipped} skipped "
          "(no cost model or no time window)")
    for r in shown:
        line = (f"  {r['fingerprint']} {r['label']:<18s}"
                f" {r['bound']:<9s} peak={r['frac_of_peak']:.2%}"
                f" bw={r['achieved_gb_per_s']:g}GB/s"
                f" fl={r['achieved_tflops']:g}TFLOPs"
                f" dev={r['device_p50_s']:.6f}s"
                f" ({r['device_time_source']})")
        if "intensity" in r:
            line += f" oi={r['intensity']:g} ridge={r['ridge']:g}"
        print(line)
    if any(r["device_time_source"] == "dispatch" for r in shown):
        print("  note: 'dispatch' rows time host dispatch, not the "
              "device — recapture with RAMBA_PERF=sync for true "
              "device windows")
    return 0


if __name__ == "__main__":
    sys.exit(main())
