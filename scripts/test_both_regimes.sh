#!/bin/sh
# Run the suite in both numerics legs (see README "Tests"):
#   x64 on  - NumPy-exact differential comparisons
#   x64 off - the TPU execution regime (32-bit lattice, relaxed tolerance)
set -e
cd "$(dirname "$0")/.."
echo "=== leg 1: x64 (NumPy-exact) ==="
python -m pytest tests/ -q "$@"
echo "=== leg 2: x32 (TPU numerics) ==="
RAMBA_TEST_X64=0 python -m pytest tests/ -q "$@"
echo "=== leg 3: RAMBA_VERIFY=1 (strict flush-time program verifier) ==="
RAMBA_VERIFY=1 python -m pytest tests/ -q "$@"
echo "=== leg 4: 2-process fault injection (RAMBA_FAULTS=compile:once) ==="
python scripts/two_process_suite.py --fault-leg
echo "=== leg 5: 2-process memory governor (tiny RAMBA_HBM_BUDGET) ==="
python scripts/two_process_suite.py --memory-leg
echo "=== leg 6: 2-process kernel cost ledger (RAMBA_PERF=1) ==="
python scripts/two_process_suite.py --perf-leg
echo "=== leg 7: 2-process serving sessions (async pipeline, coalescing) ==="
python scripts/two_process_suite.py --serving-leg
echo "=== leg 8: elastic lifecycle (2-rank checkpoint, 1-rank resume) ==="
python scripts/two_process_suite.py --elastic-leg
echo "=== leg 9: live telemetry (2-rank exporters, shared cross-rank trace) ==="
python scripts/two_process_suite.py --telemetry-leg
echo "=== leg 10: backend autotune race (2-rank, same backend latched per fingerprint) ==="
python scripts/two_process_suite.py --autotune-leg
echo "=== leg 11: 2-process rank-skewed chaos soak (coherent recovery) ==="
python scripts/two_process_suite.py --chaos-leg
echo "=== leg 12: staged resharding + live mesh elasticity (2-rank round-trip, 2->1 reshape) ==="
python scripts/two_process_suite.py --reshard-leg
echo "=== leg 13: effect-certified result memoization (2-rank lockstep cache) ==="
python scripts/two_process_suite.py --memo-leg
echo "=== leg 14: coherent load shedding (2-rank, rank-skewed serve:admit faults) ==="
python scripts/two_process_suite.py --overload-leg
echo "=== leg 15: compile classes + persistent warm start (2-rank lockstep buckets, AOT cache) ==="
python scripts/two_process_suite.py --warmstart-leg
echo "=== leg 16: critical-path attribution (2-rank lockstep stage waterfalls, rooflines) ==="
python scripts/two_process_suite.py --attrib-leg
echo "=== leg 17: fleet observability federation (3 publishers + collector, kill-mid-soak) ==="
python scripts/two_process_suite.py --fleet-leg
echo "=== leg 18: fleet serving plane (router + replicas, shared artifact tier, kill-mid-soak failover) ==="
python scripts/two_process_suite.py --router-leg
echo "=== leg 19: data integrity plane (2-rank agreed audit verdict; RAMBA_INTEGRITY=0 wrong-answer repro) ==="
python scripts/two_process_suite.py --integrity-leg
echo "=== leg 20: self-metering observability (sampled attribution lockstep, tail-based trace retention) ==="
python scripts/two_process_suite.py --sampling-leg
