#!/usr/bin/env python
"""Summarize a RAMBA_TRACE JSONL file.

Usage:
    python scripts/trace_report.py /tmp/t.jsonl [more.jsonl ...]

Accepts the path passed to RAMBA_TRACE directly; when the run was
multi-controller the per-rank files (``<path>.rank0``, ``<path>.rank1``, ...)
are discovered automatically.  Stdlib only — runs anywhere the trace file
can be copied to, no jax required.

Prints, per input:
  * health records (platform, device count, init time, fallback reasons),
  * flush totals: count, wall time, compile vs execute split, cache hit
    rate, instructions, bytes in (leaves) and out (roots),
  * rewrite-rule fire totals,
  * the degradation timeline (injected faults, retries, ladder rung
    transitions fused→split→chunked→eager→host, recoveries — newest
    last),
  * the memory timeline (admission checks, watermark crossings, spills,
    restores, oom evictions) with a peak-live column in the flush
    totals, and
  * the top programs by cumulative wall time.
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
from collections import defaultdict


def _discover(path: str) -> list:
    """The file itself, or its .rank* siblings (multi-controller runs)."""
    files = []
    import os

    if os.path.exists(path):
        files.append(path)
    files += sorted(glob.glob(glob.escape(path) + ".rank*"))
    return files


def _load(path: str) -> list:
    events = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                print(f"{path}:{ln}: unparseable line ({e})", file=sys.stderr)
    return events


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:,.1f} TB"


def report(path: str, events: list, top: int = 10, file=None) -> None:
    file = file or sys.stdout
    print(f"== {path} ({len(events)} events) ==", file=file)

    health = [e for e in events if e.get("type") == "health"]
    for h in health:
        bits = [f"{k}={h[k]}" for k in
                ("platform", "device_count", "outcome", "init_seconds",
                 "selected_via", "source") if k in h]
        print("health: " + " ".join(bits), file=file)
        if h.get("error"):
            print(f"  error: {h['error']}", file=file)

    _degradation_timeline(events, file=file)
    _memory_timeline(events, file=file)
    _findings_summary(events, file=file)

    flushes = [e for e in events if e.get("type") == "flush"]
    if not flushes:
        print("no flush spans", file=file)
        return

    wall = sum(f.get("wall_s", 0.0) for f in flushes)
    compile_s = sum(f.get("compile_s", 0.0) for f in flushes)
    execute_s = sum(f.get("execute_s", 0.0) for f in flushes)
    linearize_s = sum(f.get("linearize_s", 0.0) for f in flushes)
    hits = sum(1 for f in flushes if f.get("cache") == "hit")
    instrs = sum(f.get("instrs", 0) for f in flushes)
    leaf_b = sum(f.get("leaf_bytes", 0) for f in flushes)
    out_b = sum(f.get("out_bytes", 0) for f in flushes)
    donated = sum(f.get("donated", 0) for f in flushes)
    segs = sum(f.get("segments", 0) for f in flushes)

    print(
        f"flushes: {len(flushes)}  wall {wall:.4f}s  "
        f"(linearize {linearize_s:.4f}s, compile {compile_s:.4f}s, "
        f"execute-cached {execute_s:.4f}s)",
        file=file,
    )
    print(
        f"cache: {hits}/{len(flushes)} hit "
        f"({100.0 * hits / len(flushes):.0f}%)  "
        f"instrs: {instrs}  segments: {segs}  donated bufs: {donated}",
        file=file,
    )
    peak_live = max((f.get("mem_live_bytes", 0) or 0) for f in flushes)
    peak_est = max((f.get("mem_peak_est", 0) or 0) for f in flushes)
    line = f"bytes: in {_fmt_bytes(leaf_b)}  out {_fmt_bytes(out_b)}"
    if peak_live or peak_est:
        line += f"  peak live {_fmt_bytes(peak_live)}"
        if peak_est:
            line += f"  peak est {_fmt_bytes(peak_est)}"
    print(line, file=file)

    fires = defaultdict(int)
    for f in flushes:
        for rule, n in (f.get("rewrite_fires") or {}).items():
            fires[rule] += n
    if fires:
        print("rewrite fires: " + "  ".join(
            f"{r}={n}" for r, n in sorted(fires.items())), file=file)

    per = defaultdict(lambda: [0.0, 0, 0.0])  # label -> [wall, count, compile]
    for f in flushes:
        ent = per[f.get("label", "?")]
        ent[0] += f.get("wall_s", 0.0)
        ent[1] += 1
        ent[2] += f.get("compile_s", 0.0)
    print(f"top {min(top, len(per))} programs by wall time:", file=file)
    ranked = sorted(per.items(), key=lambda kv: -kv[1][0])[:top]
    for label, (w, cnt, comp) in ranked:
        print(
            f"  {label:<18s} {w:10.4f}s  x{cnt:<5d} compile {comp:.4f}s",
            file=file,
        )


def _findings_summary(events: list, file=None) -> None:
    """Static-analysis findings (RAMBA_VERIFY / ramba-lint) by rule and
    severity, with a sample message per bucket."""
    file = file or sys.stdout
    findings = [e for e in events if e.get("type") == "finding"]
    if not findings:
        return
    per = defaultdict(lambda: [0, ""])  # (rule, severity) -> [count, sample]
    for e in findings:
        ent = per[(e.get("rule", "?"), e.get("severity", "?"))]
        ent[0] += 1
        if not ent[1]:
            ent[1] = str(e.get("message", ""))[:60]
    print(f"verifier findings ({len(findings)}):", file=file)
    print(f"  {'rule':<20s} {'severity':<9s} {'count':>5s}  sample",
          file=file)
    sev_rank = {"error": 0, "warning": 1, "info": 2}
    for (rule, sev), (n, sample) in sorted(
        per.items(), key=lambda kv: (sev_rank.get(kv[0][1], 3), kv[0][0])
    ):
        print(f"  {rule:<20s} {sev:<9s} {n:>5d}  {sample}", file=file)


def _degradation_timeline(events: list, file=None, cap: int = 50) -> None:
    """Chronological fault/retry/degradation lines, timestamped relative to
    the first event in the trace."""
    file = file or sys.stdout
    degr = [e for e in events if e.get("type") in ("fault", "degrade")]
    if not degr:
        return
    stamps = [e["ts"] for e in events if isinstance(e.get("ts"), (int, float))]
    t0 = min(stamps) if stamps else None
    print(f"degradation timeline ({len(degr)} events):", file=file)
    for e in degr[:cap]:
        rel = (f"+{e['ts'] - t0:8.3f}s"
               if t0 is not None and isinstance(e.get("ts"), (int, float))
               else " " * 10)
        if e["type"] == "fault":
            line = (f"fault     {e.get('site', '?')} "
                    f"call={e.get('call', '?')} mode={e.get('mode', '?')}")
        else:
            action = e.get("action", "?")
            site = e.get("site", "?")
            if action == "retry":
                line = (f"retry     {site} attempt={e.get('attempt', '?')} "
                        f"delay={e.get('delay_s', 0)}s")
            elif action == "exhausted":
                line = (f"exhausted {site} "
                        f"attempts={e.get('attempts', '?')}")
            elif action == "rung":
                line = (f"degrade   {site} "
                        f"{e.get('from', '?')} -> {e.get('to', '?')}")
            elif action == "recovered":
                line = f"recovered {site} rung={e.get('rung', '?')}"
            else:
                line = f"{action} {site}"
            if e.get("error"):
                line += f"  ({str(e['error'])[:80]})"
        print(f"  {rel}  {line}", file=file)
    if len(degr) > cap:
        print(f"  ... and {len(degr) - cap} more", file=file)
    retries = sum(1 for e in degr
                  if e.get("type") == "degrade" and e.get("action") == "retry")
    rungs = sum(1 for e in degr
                if e.get("type") == "degrade" and e.get("action") == "rung")
    faults = sum(1 for e in degr if e.get("type") == "fault")
    print(f"degradation totals: faults={faults} retries={retries} "
          f"rung-steps={rungs}", file=file)


def _memory_timeline(events: list, file=None, cap: int = 50) -> None:
    """Chronological memory-governor lines (admission checks that crossed
    the watermark, spills, restores, oom evictions), timestamped relative
    to the first event in the trace.  Plain in-budget admits are elided —
    they would drown the interesting lines one-per-flush."""
    file = file or sys.stdout
    mem = [e for e in events if e.get("type") == "memory"]
    if not mem:
        return
    shown = [e for e in mem if not (e.get("action") == "admit" and e.get("ok"))]
    stamps = [e["ts"] for e in events if isinstance(e.get("ts"), (int, float))]
    t0 = min(stamps) if stamps else None
    admits = sum(1 for e in mem if e.get("action") == "admit")
    print(f"memory timeline ({len(mem)} events, {admits} admission checks):",
          file=file)
    for e in shown[:cap]:
        rel = (f"+{e['ts'] - t0:8.3f}s"
               if t0 is not None and isinstance(e.get("ts"), (int, float))
               else " " * 10)
        action = e.get("action", "?")
        if action == "admit":
            line = (f"admit     projected="
                    f"{_fmt_bytes(e.get('projected_bytes', 0))} "
                    f"est={_fmt_bytes(e.get('est_bytes', 0))} over budget")
        elif action == "watermark":
            line = (f"watermark over={_fmt_bytes(e.get('over_bytes', 0))} "
                    f"wm={_fmt_bytes(e.get('watermark_bytes', 0))}")
        elif action == "spill":
            line = (f"spill     {_fmt_bytes(e.get('bytes', 0))} "
                    f"-> host (live {_fmt_bytes(e.get('live_bytes', 0))})")
        elif action == "restore":
            line = (f"restore   {_fmt_bytes(e.get('bytes', 0))} "
                    f"-> device (live {_fmt_bytes(e.get('live_bytes', 0))})")
        elif action == "oom_evict":
            line = (f"oom-evict need={_fmt_bytes(e.get('need_bytes', 0))} "
                    f"freed={_fmt_bytes(e.get('freed_bytes', 0))}")
        elif action == "reject":
            line = (f"reject    over={_fmt_bytes(e.get('over_bytes', 0))} "
                    f"freed={_fmt_bytes(e.get('freed_bytes', 0))} "
                    f"route={e.get('route', '?')}")
        else:
            line = action
        print(f"  {rel}  {line}", file=file)
    if len(shown) > cap:
        print(f"  ... and {len(shown) - cap} more", file=file)
    spills = sum(1 for e in mem if e.get("action") == "spill")
    restores = sum(1 for e in mem if e.get("action") == "restore")
    rejects = sum(1 for e in mem if e.get("action") == "reject")
    print(f"memory totals: spills={spills} restores={restores} "
          f"rejects={rejects}", file=file)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize RAMBA_TRACE JSONL trace files."
    )
    ap.add_argument("paths", nargs="+",
                    help="trace file(s); .rank* siblings auto-discovered")
    ap.add_argument("--top", type=int, default=10,
                    help="programs to list (default 10)")
    args = ap.parse_args(argv)

    files = []
    for p in args.paths:
        found = _discover(p)
        if not found:
            print(f"{p}: no trace file found", file=sys.stderr)
            return 2
        files += [f for f in found if f not in files]

    for f in files:
        report(f, _load(f), top=args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
