#!/usr/bin/env python
"""Summarize a RAMBA_TRACE JSONL file.

Usage:
    python scripts/trace_report.py /tmp/t.jsonl [more.jsonl ...]

Accepts the path passed to RAMBA_TRACE directly; when the run was
multi-controller the per-rank files (``<path>.rank0``, ``<path>.rank1``, ...)
are discovered automatically.  Stdlib only — runs anywhere the trace file
can be copied to, no jax required.

Prints, per input:
  * health records (platform, device count, init time, fallback reasons),
  * flush totals: count, wall time, compile vs execute split, cache hit
    rate, instructions, bytes in (leaves) and out (roots),
  * rewrite-rule fire totals,
  * the degradation timeline (injected faults, retries, ladder rung
    transitions fused→split→chunked→eager→host, recoveries — newest
    last),
  * the memory timeline (admission checks, watermark crossings, spills,
    restores, oom evictions) with a peak-live column in the flush
    totals,
  * the elastic lifecycle timeline (watchdog stalls, drains,
    checkpoints, resumes, heartbeat misses) plus a per-rank heartbeat
    liveness summary that flags gaps wider than 2x the beacon interval
    — the offline signature of a wedged rank,
  * slow_flush sentinel events (observe/ledger.py), and
  * the top programs by cumulative wall time.

``--merge-ranks`` switches to a cross-rank view: per-rank files are
aligned by their distributed bring-up anchor (clock skew subtracted),
interleaved into one timeline, and the per-rank flush streams are
compared in lockstep order to flag rank divergence (e.g. one rank
degraded to ``chunked`` while another stayed ``fused``, or the two
stamped different stage signatures for the same flush index).

``--attrib`` switches to the stage-waterfall view of the attribution
plane (observe/attrib.py): per-program stage decomposition of flush
wall time (prepare / verify / queue_wait / coalesce / compile / admit /
dispatch / device_execute / write_back), recent per-flush waterfalls,
and the top programs by unattributed gap — the wall-clock the stage
ledger could NOT explain, which is where to dig first.
"""

from __future__ import annotations

import argparse
import glob
import json
import sys
from collections import defaultdict

# canonical stage order (mirrors ramba_tpu.observe.attrib.STAGES —
# duplicated so this script stays stdlib-only / copyable off-host)
STAGE_ORDER = ("trace", "prepare", "verify", "queue_wait", "coalesce",
               "compile",
               "admit", "dispatch", "device_execute", "write_back")


def _stage_sig(flush: dict) -> str:
    """Order-stable stage signature of one flush span ('' when the span
    predates the stage ledger).  A span with ``device_source:
    "estimated"`` skipped its device fence by SAMPLING POLICY
    (RAMBA_ATTRIB=sample:<N>), not by behavior — normalize it as if the
    fence had fired, so estimated-vs-fenced never reads as a rank
    divergence while a genuinely missing fence still does."""
    st = flush.get("stages") or {}
    estimated = (flush.get("device_source") == "estimated")
    return ",".join(
        k for k in STAGE_ORDER
        if k in st or (estimated and k == "device_execute"))


def _discover(path: str) -> list:
    """The file itself, or its .rank* siblings (multi-controller runs).
    A DIRECTORY discovers every trace JSONL beneath it — the fleet
    layout, where each replica process wrote its own trace dir/file."""
    import os

    if os.path.isdir(path):
        return _walk_fleet_dir(path)
    files = []
    if os.path.exists(path):
        files.append(path)
    files += sorted(glob.glob(glob.escape(path) + ".rank*"))
    return files


def _walk_fleet_dir(root: str) -> list:
    """Every ``*.jsonl`` / ``*.jsonl.rank<i>`` file under ``root``,
    sorted — one entry per per-process trace stream."""
    import os

    out = []
    for dirpath, _dirs, names in os.walk(root):
        for name in sorted(names):
            if ".jsonl" in name and not name.endswith(".tmp"):
                out.append(os.path.join(dirpath, name))
    return sorted(out)


def _rname(r) -> str:
    """Display name of one stream key: SPMD ranks are ints (``r0``),
    fleet replicas are path-derived string labels used verbatim."""
    return f"r{r}" if isinstance(r, int) else str(r)


def _load_streams(path: str):
    """``{stream_key: [events]}`` for one input.  A plain file keys its
    ``.rank<i>`` siblings by integer rank; a directory keys each
    discovered file by its relative path (the replica label), so two
    replicas that each called themselves rank 0 stay distinct streams.
    Returns None when nothing was found."""
    import os

    if os.path.isdir(path):
        streams: dict = {}
        for f in _walk_fleet_dir(path):
            label = os.path.relpath(f, path).replace(os.sep, "/")
            label = label.replace(".jsonl", "") or label
            streams.setdefault(label, []).extend(_load(f))
        return streams or None
    found = _discover(path)
    if not found:
        return None
    streams = {}
    for f in found:
        evs = _load(f)
        streams.setdefault(_file_rank(f, evs), []).extend(evs)
    return streams


def _load(path: str) -> list:
    events = []
    with open(path) as f:
        for ln, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError as e:
                print(f"{path}:{ln}: unparseable line ({e})", file=sys.stderr)
    return events


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if abs(n) < 1024 or unit == "TB":
            return f"{n:,.1f} {unit}" if unit != "B" else f"{int(n)} B"
        n /= 1024
    return f"{n:,.1f} TB"


def report(path: str, events: list, top: int = 10, file=None) -> None:
    file = file or sys.stdout
    print(f"== {path} ({len(events)} events) ==", file=file)

    health = [e for e in events if e.get("type") == "health"]
    for h in health:
        bits = [f"{k}={h[k]}" for k in
                ("platform", "device_count", "outcome", "init_seconds",
                 "selected_via", "source") if k in h]
        print("health: " + " ".join(bits), file=file)
        if h.get("error"):
            print(f"  error: {h['error']}", file=file)

    _degradation_timeline(events, file=file)
    _memory_timeline(events, file=file)
    _lifecycle_timeline(events, file=file)
    _findings_summary(events, file=file)
    _slow_flush_summary(events, file=file)

    flushes = [e for e in events if e.get("type") == "flush"]
    if not flushes:
        print("no flush spans", file=file)
        return

    wall = sum(f.get("wall_s", 0.0) for f in flushes)
    compile_s = sum(f.get("compile_s", 0.0) for f in flushes)
    execute_s = sum(f.get("execute_s", 0.0) for f in flushes)
    linearize_s = sum(f.get("linearize_s", 0.0) for f in flushes)
    hits = sum(1 for f in flushes if f.get("cache") == "hit")
    memo_hits = sum(1 for f in flushes if f.get("cache") == "memo")
    instrs = sum(f.get("instrs", 0) for f in flushes)
    leaf_b = sum(f.get("leaf_bytes", 0) for f in flushes)
    out_b = sum(f.get("out_bytes", 0) for f in flushes)
    donated = sum(f.get("donated", 0) for f in flushes)
    segs = sum(f.get("segments", 0) for f in flushes)

    print(
        f"flushes: {len(flushes)}  wall {wall:.4f}s  "
        f"(linearize {linearize_s:.4f}s, compile {compile_s:.4f}s, "
        f"execute-cached {execute_s:.4f}s)",
        file=file,
    )
    line = (
        f"cache: {hits}/{len(flushes)} hit "
        f"({100.0 * hits / len(flushes):.0f}%)  "
        f"instrs: {instrs}  segments: {segs}  donated bufs: {donated}"
    )
    if memo_hits:
        line += f"  memo hits: {memo_hits}"
    print(line, file=file)
    # compile-class + warm-pool attribution (PR-14): `compile` events are
    # source-tagged by the ledger; bucketed spans carry compile_class
    compiles = [e for e in events if e.get("type") == "compile"]
    bucketed = [f for f in flushes if f.get("compile_class")]
    if compiles or bucketed:
        warm = [e for e in compiles if e.get("source") == "warm"]
        warm_s = sum(e.get("seconds", 0.0) for e in warm)
        all_s = sum(e.get("seconds", 0.0) for e in compiles)
        line = (f"compiles: {len(compiles)} "
                f"({len(warm)} warm {warm_s:.4f}s / "
                f"{len(compiles) - len(warm)} demand "
                f"{all_s - warm_s:.4f}s)")
        if bucketed:
            waste = sum(f.get("pad_waste_bytes", 0) for f in bucketed)
            classes = sorted({tuple(f["compile_class"]) for f in bucketed})
            line += (f"  bucketed flushes: {len(bucketed)}"
                     f" classes: {len(classes)}"
                     f" pad waste: {_fmt_bytes(waste)}")
        print(line, file=file)
    # plan-certificate cache (PR-18): hits skip the prepare-side
    # analysis pipeline; stale events name the invalidation causes
    plan_hits = sum(1 for f in flushes if f.get("plan_cache"))
    plan_stale = [e for e in events if e.get("type") == "plan_stale"]
    if plan_hits or plan_stale:
        shared = sum(1 for f in flushes
                     if f.get("plan_cache") == "shared")
        line = (f"plan cache: {plan_hits}/{len(flushes)} flushes on the "
                f"fast path ({100.0 * plan_hits / len(flushes):.0f}%)")
        if shared:
            line += f"  adopted from shared tier: {shared}"
        if plan_stale:
            causes = defaultdict(int)
            forged = 0
            for e in plan_stale:
                if e.get("forged"):
                    forged += 1
                for c in e.get("causes", ()):
                    causes[str(c)] += 1
            cs = "  ".join(f"{c}={n}"
                           for c, n in sorted(causes.items()))
            line += f"  stale: {len(plan_stale)}"
            if forged:
                line += f" (forged: {forged})"
            if cs:
                line += f" causes: {cs}"
        print(line, file=file)
    cse = [e for e in events if e.get("type") == "cse_merge"]
    if memo_hits or cse:
        rejected = sum(1 for e in events
                       if e.get("type") == "memo_insert_rejected")
        line = (f"result memo: {memo_hits}/{len(flushes)} flushes served "
                f"from cache ({100.0 * memo_hits / len(flushes):.0f}%)")
        if cse:
            line += f"  cse merges: {len(cse)}"
        if rejected:
            line += f"  uncertified inserts rejected: {rejected}"
        print(line, file=file)
    peak_live = max((f.get("mem_live_bytes", 0) or 0) for f in flushes)
    peak_est = max((f.get("mem_peak_est", 0) or 0) for f in flushes)
    line = f"bytes: in {_fmt_bytes(leaf_b)}  out {_fmt_bytes(out_b)}"
    if peak_live or peak_est:
        line += f"  peak live {_fmt_bytes(peak_live)}"
        if peak_est:
            line += f"  peak est {_fmt_bytes(peak_est)}"
    print(line, file=file)

    fires = defaultdict(int)
    for f in flushes:
        for rule, n in (f.get("rewrite_fires") or {}).items():
            fires[rule] += n
    if fires:
        print("rewrite fires: " + "  ".join(
            f"{r}={n}" for r, n in sorted(fires.items())), file=file)

    # serving runs tag spans with the tenant; the table grows a tenant
    # column (and a per-tenant totals block) only when one is present, so
    # single-stream traces render exactly as before
    tenanted = any("tenant" in f for f in flushes)
    if tenanted:
        per_tenant = defaultdict(lambda: [0.0, 0, 0])  # [wall, count, queued]
        for f in flushes:
            ent = per_tenant[f.get("tenant", "-")]
            ent[0] += f.get("wall_s", 0.0)
            ent[1] += 1
            ent[2] += 1 if "queue_s" in f else 0
        coalesced = [e for e in events if e.get("type") == "serve_coalesce"]
        print("per-tenant flush totals:", file=file)
        for t, (w, cnt, quo) in sorted(per_tenant.items(),
                                       key=lambda kv: -kv[1][0]):
            print(f"  {t:<18s} {w:10.4f}s  x{cnt:<5d} async {quo}",
                  file=file)
        if coalesced:
            n = sum(e.get("n", 0) for e in coalesced)
            print(f"coalesced batches: {len(coalesced)} "
                  f"({n} flushes merged)", file=file)

    # label -> [wall, count, compile, tenants]
    per = defaultdict(lambda: [0.0, 0, 0.0, set()])
    for f in flushes:
        ent = per[f.get("label", "?")]
        ent[0] += f.get("wall_s", 0.0)
        ent[1] += 1
        ent[2] += f.get("compile_s", 0.0)
        if "tenant" in f:
            ent[3].add(f["tenant"])
    print(f"top {min(top, len(per))} programs by wall time:", file=file)
    ranked = sorted(per.items(), key=lambda kv: -kv[1][0])[:top]
    for label, (w, cnt, comp, tenants) in ranked:
        line = f"  {label:<18s} {w:10.4f}s  x{cnt:<5d} compile {comp:.4f}s"
        if tenanted:
            line += f"  tenant {','.join(sorted(tenants)) or '-'}"
        print(line, file=file)


def _findings_summary(events: list, file=None) -> None:
    """Static-analysis findings (RAMBA_VERIFY / ramba-lint) by rule and
    severity, with a sample message per bucket."""
    file = file or sys.stdout
    findings = [e for e in events if e.get("type") == "finding"]
    if not findings:
        return
    per = defaultdict(lambda: [0, ""])  # (rule, severity) -> [count, sample]
    for e in findings:
        ent = per[(e.get("rule", "?"), e.get("severity", "?"))]
        ent[0] += 1
        if not ent[1]:
            ent[1] = str(e.get("message", ""))[:60]
    print(f"verifier findings ({len(findings)}):", file=file)
    print(f"  {'rule':<20s} {'severity':<9s} {'count':>5s}  sample",
          file=file)
    sev_rank = {"error": 0, "warning": 1, "info": 2}
    for (rule, sev), (n, sample) in sorted(
        per.items(), key=lambda kv: (sev_rank.get(kv[0][1], 3), kv[0][0])
    ):
        print(f"  {rule:<20s} {sev:<9s} {n:>5d}  {sample}", file=file)


def _slow_flush_summary(events: list, file=None, cap: int = 20) -> None:
    """slow_flush sentinel events (observe/ledger.py): flushes that blew
    past RAMBA_SLOW_FLUSH_FACTOR x their program's rolling p50, with the
    rung they ran on and compile-vs-execute attribution."""
    file = file or sys.stdout
    slow = [e for e in events if e.get("type") == "slow_flush"]
    if not slow:
        return
    print(f"slow flushes ({len(slow)}):", file=file)
    for e in slow[:cap]:
        print(
            f"  {e.get('label', '?'):<18s} rung={e.get('rung', '?'):<8s}"
            f" wall={e.get('wall_s', 0):.4f}s"
            f" p50={e.get('p50_s', 0):.4f}s x{e.get('slowdown', 0)}"
            f" compile={e.get('compile_s', 0)}s"
            f" execute={e.get('execute_s', 0)}s"
            f" cache={e.get('cache', '?')}",
            file=file,
        )
    if len(slow) > cap:
        print(f"  ... and {len(slow) - cap} more", file=file)


def _degradation_timeline(events: list, file=None, cap: int = 50) -> None:
    """Chronological fault/retry/degradation lines, timestamped relative to
    the first event in the trace."""
    file = file or sys.stdout
    degr = [e for e in events if e.get("type") in ("fault", "degrade")]
    if not degr:
        return
    stamps = [e["ts"] for e in events if isinstance(e.get("ts"), (int, float))]
    t0 = min(stamps) if stamps else None
    print(f"degradation timeline ({len(degr)} events):", file=file)
    for e in degr[:cap]:
        rel = (f"+{e['ts'] - t0:8.3f}s"
               if t0 is not None and isinstance(e.get("ts"), (int, float))
               else " " * 10)
        if e["type"] == "fault":
            line = (f"fault     {e.get('site', '?')} "
                    f"call={e.get('call', '?')} mode={e.get('mode', '?')}")
        else:
            action = e.get("action", "?")
            site = e.get("site", "?")
            if action == "retry":
                line = (f"retry     {site} attempt={e.get('attempt', '?')} "
                        f"delay={e.get('delay_s', 0)}s")
            elif action == "exhausted":
                line = (f"exhausted {site} "
                        f"attempts={e.get('attempts', '?')}")
            elif action == "rung":
                line = (f"degrade   {site} "
                        f"{e.get('from', '?')} -> {e.get('to', '?')}")
            elif action == "recovered":
                line = f"recovered {site} rung={e.get('rung', '?')}"
            else:
                line = f"{action} {site}"
            if e.get("error"):
                line += f"  ({str(e['error'])[:80]})"
        print(f"  {rel}  {line}", file=file)
    if len(degr) > cap:
        print(f"  ... and {len(degr) - cap} more", file=file)
    retries = sum(1 for e in degr
                  if e.get("type") == "degrade" and e.get("action") == "retry")
    rungs = sum(1 for e in degr
                if e.get("type") == "degrade" and e.get("action") == "rung")
    faults = sum(1 for e in degr if e.get("type") == "fault")
    print(f"degradation totals: faults={faults} retries={retries} "
          f"rung-steps={rungs}", file=file)


def _memory_timeline(events: list, file=None, cap: int = 50) -> None:
    """Chronological memory-governor lines (admission checks that crossed
    the watermark, spills, restores, oom evictions), timestamped relative
    to the first event in the trace.  Plain in-budget admits are elided —
    they would drown the interesting lines one-per-flush."""
    file = file or sys.stdout
    mem = [e for e in events if e.get("type") == "memory"]
    if not mem:
        return
    shown = [e for e in mem if not (e.get("action") == "admit" and e.get("ok"))]
    stamps = [e["ts"] for e in events if isinstance(e.get("ts"), (int, float))]
    t0 = min(stamps) if stamps else None
    admits = sum(1 for e in mem if e.get("action") == "admit")
    print(f"memory timeline ({len(mem)} events, {admits} admission checks):",
          file=file)
    for e in shown[:cap]:
        rel = (f"+{e['ts'] - t0:8.3f}s"
               if t0 is not None and isinstance(e.get("ts"), (int, float))
               else " " * 10)
        action = e.get("action", "?")
        if action == "admit":
            line = (f"admit     projected="
                    f"{_fmt_bytes(e.get('projected_bytes', 0))} "
                    f"est={_fmt_bytes(e.get('est_bytes', 0))} over budget")
        elif action == "watermark":
            line = (f"watermark over={_fmt_bytes(e.get('over_bytes', 0))} "
                    f"wm={_fmt_bytes(e.get('watermark_bytes', 0))}")
        elif action == "spill":
            line = (f"spill     {_fmt_bytes(e.get('bytes', 0))} "
                    f"-> host (live {_fmt_bytes(e.get('live_bytes', 0))})")
        elif action == "restore":
            line = (f"restore   {_fmt_bytes(e.get('bytes', 0))} "
                    f"-> device (live {_fmt_bytes(e.get('live_bytes', 0))})")
        elif action == "oom_evict":
            line = (f"oom-evict need={_fmt_bytes(e.get('need_bytes', 0))} "
                    f"freed={_fmt_bytes(e.get('freed_bytes', 0))}")
        elif action == "reject":
            line = (f"reject    over={_fmt_bytes(e.get('over_bytes', 0))} "
                    f"freed={_fmt_bytes(e.get('freed_bytes', 0))} "
                    f"route={e.get('route', '?')}")
        else:
            line = action
        print(f"  {rel}  {line}", file=file)
    if len(shown) > cap:
        print(f"  ... and {len(shown) - cap} more", file=file)
    spills = sum(1 for e in mem if e.get("action") == "spill")
    restores = sum(1 for e in mem if e.get("action") == "restore")
    rejects = sum(1 for e in mem if e.get("action") == "reject")
    print(f"memory totals: spills={spills} restores={restores} "
          f"rejects={rejects}", file=file)


def _lifecycle_timeline(events: list, file=None, cap: int = 40) -> None:
    """Elastic job-lifecycle lines (watchdog stalls, drain / checkpoint /
    resume phases, heartbeat misses) plus a heartbeat liveness summary.

    Heartbeats themselves are volume (one per RAMBA_HEARTBEAT_S), so
    they are rolled up rather than listed: beat count, observed beacon
    span, and every inter-beat gap wider than 2x the interval — a rank
    that went silent mid-run shows up here as a flagged gap even though
    no single event says so."""
    file = file or sys.stdout
    beats = [e for e in events if e.get("type") == "heartbeat"]
    life = [e for e in events if e.get("type") in ("stall", "lifecycle")]
    if not beats and not life:
        return
    stamps = [e["ts"] for e in events if isinstance(e.get("ts"), (int, float))]
    t0 = min(stamps) if stamps else None

    def rel(e):
        return (f"+{e['ts'] - t0:8.3f}s"
                if t0 is not None and isinstance(e.get("ts"), (int, float))
                else " " * 10)

    if life:
        print(f"lifecycle timeline ({len(life)} events):", file=file)
        for e in life[:cap]:
            if e["type"] == "stall":
                line = (f"STALL     {e.get('site', '?')} "
                        f"waited={e.get('waited_s', '?')}s "
                        f"deadline={e.get('deadline_s', '?')}s "
                        f"class={e.get('classification', '?')}")
            else:
                phase = e.get("phase", "?")
                line = f"{phase:<9s}"
                for k in ("step", "streams", "age_s", "limit_s",
                          "deleted_steps", "from_processes", "to_processes",
                          "freed_bytes", "wall_s"):
                    if e.get(k) is not None:
                        line += f" {k}={e[k]}"
            print(f"  {rel(e)}  {line}", file=file)
        if len(life) > cap:
            print(f"  ... and {len(life) - cap} more", file=file)
        stalls = sum(1 for e in life if e["type"] == "stall")
        misses = sum(1 for e in life if e.get("phase") == "heartbeat_missed")
        saves = sum(1 for e in life if e.get("phase") == "checkpoint_saved")
        resumes = sum(1 for e in life if e.get("phase") == "resume_complete")
        print(f"lifecycle totals: stalls={stalls} heartbeat-misses={misses} "
              f"checkpoints={saves} resumes={resumes}", file=file)

    if beats:
        interval = beats[-1].get("interval_s") or 0.0
        # Inter-beat gaps use the monotonic clock when every beat carries
        # one (events gained ``mono`` alongside ``ts``): an NTP step
        # between two beats would otherwise fabricate — or hide — a gap.
        # Wall clock only for older traces.
        if all(isinstance(e.get("mono"), (int, float)) for e in beats):
            stamped = [(e["mono"], e.get("ts")) for e in beats]
        else:
            stamped = [(e["ts"], e["ts"]) for e in beats
                       if isinstance(e.get("ts"), (int, float))]
        span = (stamped[-1][0] - stamped[0][0]) if len(stamped) > 1 else 0.0
        print(f"heartbeat: {len(beats)} beats over {span:.3f}s "
              f"(interval {interval}s)", file=file)
        limit = 2.0 * interval if interval else None
        flagged = 0
        for (a, a_ts), (b, _b_ts) in zip(stamped, stamped[1:]):
            gap = b - a
            if limit is not None and gap > limit:
                flagged += 1
                r = (f"+{a_ts - t0:8.3f}s"
                     if t0 is not None and isinstance(a_ts, (int, float))
                     else " " * 10)
                print(f"  {r}  GAP {gap:.3f}s > 2x interval "
                      f"({limit:.3f}s) — rank silent", file=file)
        if limit is not None and not flagged:
            print(f"  no gaps over 2x interval ({limit:.3f}s)", file=file)


def _file_rank(path: str, events: list) -> int:
    """Rank of one trace file: the ``.rank<i>`` filename suffix wins,
    else the first event carrying a ``rank`` field, else 0."""
    import re

    m = re.search(r"\.rank(\d+)$", path)
    if m:
        return int(m.group(1))
    for e in events:
        r = e.get("rank")
        if isinstance(r, int):
            return r
    return 0


def _anchor(events: list):
    """Per-rank alignment anchor ``(ts, mono)``: the distributed bring-up
    health record is the one event every rank emits at (nearly) the same
    real moment — the group barrier inside jax.distributed.initialize.
    Fallback: any health record (mesh bring-up).  Returns None when the
    rank has NO health event at all; the caller must then treat the rank
    as unanchored (skew 0) rather than misalign it off its first event,
    whose real-world moment is arbitrary.  ``mono`` rides along so later
    per-rank deltas can use the monotonic clock (immune to NTP steps);
    it is None for traces written before events carried ``mono``."""
    for pred in (
        lambda e: e.get("type") == "health"
        and e.get("source") == "distributed_init",
        lambda e: e.get("type") == "health",
    ):
        for e in events:
            if pred(e) and isinstance(e.get("ts"), (int, float)):
                mono = e.get("mono")
                return (e["ts"],
                        mono if isinstance(mono, (int, float)) else None)
    return None


def _anchor_ts(events: list):
    """Back-compat shim: the wall-clock half of :func:`_anchor`."""
    a = _anchor(events)
    return a[0] if a is not None else None


def _merge_line(e: dict) -> str:
    """One compact description for the merged timeline."""
    t = e.get("type", "?")
    if t == "health":
        return (f"health    {e.get('source', '?')}"
                f" outcome={e.get('outcome', '?')}")
    if t == "fault":
        return (f"fault     {e.get('site', '?')} mode={e.get('mode', '?')}"
                f" call={e.get('call', '?')}")
    if t == "degrade":
        return (f"degrade   {e.get('site', '?')} {e.get('action', '?')}"
                f" {e.get('from', '')}->{e.get('to', '')}")
    if t == "slow_flush":
        return (f"slow_flush {e.get('label', '?')}"
                f" rung={e.get('rung', '?')} x{e.get('slowdown', '?')}")
    if t == "cache_evict":
        return f"cache_evict {e.get('key', '?')}"
    if t == "flush_error":
        line = f"flush_err {e.get('label', '?')}"
        if e.get("tenant"):
            line += f" tenant={e['tenant']}"
        return line + f" {str(e.get('error', ''))[:60]}"
    if t == "serve_coalesce":
        return (f"coalesce  fp={e.get('fingerprint', '?')}"
                f" n={e.get('n', '?')}"
                f" tenants={','.join(e.get('tenants') or [])}")
    if t == "serve_session":
        line = f"session   stream={e.get('stream', '?')}"
        if e.get("tenant"):
            line += f" tenant={e['tenant']}"
        return line
    if t == "slo_breach":
        return (f"SLO-BREACH tenant={e.get('tenant', '-')}"
                f" p95={e.get('p95_ms', '?')}ms"
                f" objective={e.get('objective_ms', '?')}ms"
                f" samples={e.get('samples', '?')}")
    if t == "program":
        instrs = e.get("instrs")
        n = len(instrs) if isinstance(instrs, list) else instrs
        return f"program   {e.get('label', '?')} instrs={n}"
    if t == "plan_stale":
        causes = ",".join(e.get("causes") or []) or "?"
        tag = " FORGED" if e.get("forged") else ""
        return (f"plan_stale {e.get('label', '?')}"
                f" causes={causes}{tag}")
    if t == "plan_divergence":
        return (f"plan_diverge proposed={e.get('proposed', '?')}"
                f" agreed={e.get('agreed', '?')} (cache cleared)")
    if t == "memory":
        return (f"memory    {e.get('action', '?')}"
                f" {_fmt_bytes(e.get('bytes', e.get('over_bytes', 0)) or 0)}")
    if t == "stall":
        return (f"STALL     {e.get('site', '?')}"
                f" waited={e.get('waited_s', '?')}s"
                f" class={e.get('classification', '?')}")
    if t == "coherence":
        line = (f"coherence {e.get('site', '?')}"
                f" epoch={e.get('epoch', '?')}"
                f" {e.get('proposal', '?')}->{e.get('decision', '?')}")
        if e.get("outcome") == "local":
            line += " LOCAL-FALLBACK"
        return line
    if t == "lifecycle":
        line = f"lifecycle {e.get('phase', '?')}"
        if e.get("step") is not None:
            line += f" step={e['step']}"
        return line
    if t == "reshard":
        a = e.get("action", "?")
        line = f"reshard   {a} epoch={e.get('epoch', '?')}"
        if a == "plan":
            line += (f" stages={e.get('stages', '?')}"
                     f" {_fmt_bytes(e.get('bytes', 0) or 0)}"
                     f" peak<={_fmt_bytes(e.get('peak_bound_bytes', 0) or 0)}")
        elif a == "stage":
            line += (f" stage={e.get('stage', '?')}"
                     f" {_fmt_bytes(e.get('bytes', 0) or 0)}")
        elif a == "rollback":
            line += f" ROLLBACK {str(e.get('error', ''))[:60]}"
        else:
            line += f" {_fmt_bytes(e.get('bytes', 0) or 0)}"
        return line
    if t == "flush":
        return (f"flush     {e.get('label', '?')}"
                f" rung={e.get('degraded', 'fused')}"
                f" wall={e.get('wall_s', 0):.4f}s")
    if t == "shed":
        line = (f"shed      {e.get('reason', '?')}"
                f" stage={e.get('stage', '?')}")
        if e.get("label"):
            line += f" {e['label']}"
        if e.get("tenant"):
            line += f" tenant={e['tenant']}"
        if e.get("epoch") is not None:
            line += f" epoch={e['epoch']}"
        return line
    if t == "breaker":
        line = (f"breaker   tenant={e.get('tenant', '?')}"
                f" {e.get('from', '?')}->{e.get('to', '?')}"
                f" failures={e.get('failures', '?')}")
        if e.get("to") == "open":
            line += " TRIPPED"
        return line
    if t == "hedge":
        line = f"hedge     {e.get('action', '?')} {e.get('label', '?')}"
        if e.get("action") == "fired":
            line += (f" threshold={e.get('threshold_ms', '?')}ms"
                     f" waited={e.get('waited_ms', '?')}ms")
        elif e.get("action") == "resolved":
            line += (f" winner={e.get('winner', '?')}"
                     f" wall={e.get('wall_ms', '?')}ms")
        return line
    if t == "brownout":
        return (f"brownout  {e.get('from', '?')}->{e.get('to', '?')}"
                f" queue={e.get('queue_ratio', '?')}"
                f" mem={e.get('memory_frac', '?')}"
                f" slo_breached={e.get('slo_breached', '?')}")
    if t == "redirect":
        sid = str(e.get("sid") or "?")
        return (f"redirect  {e.get('reason', '?')}"
                f" sid={sid[:8]}"
                f" {e.get('from', '?')}->{e.get('to') or '(reroute)'}"
                f" class={e.get('classification', '?')}"
                + (f" tenant={e['tenant']}" if e.get("tenant") else ""))
    if t == "heal":
        sid = str(e.get("sid") or "?")
        return (f"heal      {e.get('how', '?')} sid={sid[:8]}"
                f" {e.get('from', '?')}->{e.get('to', '?')}"
                f" replayed={e.get('steps_replayed', '?')}"
                f" wall={e.get('wall_ms', '?')}ms"
                + (f" tenant={e['tenant']}" if e.get("tenant") else ""))
    if t == "migrate":
        sid = str(e.get("sid") or "?")
        line = f"migrate   {e.get('action', '?')} sid={sid[:8]}"
        if e.get("from") or e.get("to"):
            line += f" {e.get('from', '?')}->{e.get('to', '?')}"
        if e.get("wall_ms") is not None:
            line += f" wall={e['wall_ms']}ms"
        if e.get("tenant"):
            line += f" tenant={e['tenant']}"
        return line
    if t == "replica":
        return (f"replica   {e.get('action', '?')}"
                f" {e.get('endpoint', '?')}")
    return t


def merge_report(path: str, per_rank: dict, file=None, cap: int = 80) -> None:
    """Cross-rank merged timeline + rank-divergence analysis.

    ``per_rank`` maps rank -> event list; keys are integer SPMD ranks
    for file inputs and replica path labels for directory (fleet)
    inputs — the analysis is identical.  Per-rank clock skew is
    estimated from the bring-up anchor (see ``_anchor_ts``) and
    subtracted, then all ranks' noteworthy events are interleaved by
    adjusted timestamp (seq breaks ties within a rank).  Divergence
    check: walking each rank's flush stream in lockstep order, every
    position where ranks disagree on program label or degradation rung
    is flagged — one rank degrading to ``chunked`` while another stayed
    ``fused`` is how SPMD runs deadlock in collectives, and it is
    invisible in any single-rank view."""
    file = file or sys.stdout
    ranks = sorted(per_rank)
    total = sum(len(v) for v in per_rank.values())
    print(f"== merged timeline: {path} ({len(ranks)} rank(s), "
          f"{total} events) ==", file=file)
    anchors = {r: _anchor(per_rank[r]) for r in ranks}
    known = [a[0] for a in anchors.values() if a is not None]
    base = min(known) if known else 0.0
    skew = {}
    for r in ranks:
        if anchors[r] is None:
            # No bring-up anchor in this rank's file (e.g. it crashed
            # before initialize, or the file is a fragment).  Skew 0 is
            # honest — any other offset would be invented — but the
            # timeline reader must know this rank floats.
            skew[r] = 0.0
            print(f"rank {_rname(r)}: no bring-up anchor event — UNANCHORED "
                  "(skew 0 assumed, cross-rank ordering approximate)",
                  file=file)
        else:
            skew[r] = anchors[r][0] - base
    print("rank skew (vs earliest anchor): " + "  ".join(
        f"{_rname(r)}={skew[r]:+.4f}s" for r in ranks), file=file)

    def _adjusted(r: int, e: dict):
        """Event time on the common (earliest-anchor) axis.  When both
        the rank's anchor and the event carry ``mono``, the offset from
        the anchor uses the monotonic clock — an NTP step between
        bring-up and the event cannot warp the timeline.  Wall-clock
        minus skew otherwise."""
        a = anchors[r]
        mono = e.get("mono")
        if (a is not None and a[1] is not None
                and isinstance(mono, (int, float))):
            return base + (mono - a[1])
        ts = e.get("ts")
        if not isinstance(ts, (int, float)):
            return None
        return ts - skew[r]

    merged = []
    for r in ranks:
        for e in per_rank[r]:
            adj = _adjusted(r, e)
            if adj is None:
                continue
            merged.append((adj, e.get("seq", 0), r, e))
    merged.sort(key=lambda t: (t[0], t[1], t[2]))
    t0 = merged[0][0] if merged else 0.0

    def noteworthy(e: dict) -> bool:
        t = e.get("type")
        if t in ("fault", "degrade", "slow_flush", "cache_evict",
                 "flush_error", "health", "serve_coalesce", "stall",
                 "lifecycle", "coherence", "reshard", "shed", "breaker",
                 "hedge", "brownout", "redirect", "heal", "migrate",
                 "replica", "plan_stale", "plan_divergence"):
            return True
        if t == "memory":
            return not (e.get("action") == "admit" and e.get("ok"))
        if t == "flush":
            return "degraded" in e
        return False

    shown = [m for m in merged if noteworthy(m[3])]
    print(f"noteworthy events ({len(shown)} of {len(merged)} stamped):",
          file=file)
    for adj, _seq, r, e in shown[:cap]:
        print(f"  +{adj - t0:8.3f}s {_rname(r)}  {_merge_line(e)}", file=file)
    if len(shown) > cap:
        print(f"  ... and {len(shown) - cap} more", file=file)

    # --- rank divergence over the lockstep flush streams ---
    streams = {
        r: [e for e in per_rank[r] if e.get("type") == "flush"]
        for r in ranks
    }
    counts = {r: len(streams[r]) for r in ranks}
    if len(ranks) < 2:
        print("rank divergence: single rank, nothing to compare", file=file)
        return
    diverged = []
    depth = min(counts.values())
    for i in range(depth):
        labels = {r: streams[r][i].get("label", "?") for r in ranks}
        rungs = {r: streams[r][i].get("degraded", "fused") for r in ranks}
        sigs = {r: _stage_sig(streams[r][i]) for r in ranks}
        if (len(set(labels.values())) > 1 or len(set(rungs.values())) > 1
                or len(set(sigs.values())) > 1):
            diverged.append((i, labels, rungs, sigs))
    if len(set(counts.values())) > 1:
        print("rank divergence: flush-count mismatch " + "  ".join(
            f"{_rname(r)}={counts[r]}" for r in ranks), file=file)
    for i, labels, rungs, sigs in diverged[:20]:
        line = f"rank divergence at flush #{i}: " + "  ".join(
            f"{_rname(r)}={labels[r]}/{rungs[r]}" for r in ranks)
        if len(set(sigs.values())) > 1:
            line += "  stages " + "  ".join(
                f"{_rname(r)}=[{sigs[r]}]" for r in ranks)
        print(line, file=file)
    if len(diverged) > 20:
        print(f"  ... and {len(diverged) - 20} more", file=file)
    if not diverged and len(set(counts.values())) == 1:
        print(f"rank divergence: none ({depth} lockstep flushes, "
              "labels, rungs and stage signatures agree)", file=file)
    # per-rank stage-seconds columns: a rank burning its wall in a
    # different stage than its peers is the cross-rank perf smell the
    # lockstep labels above can't show
    totals = {r: defaultdict(float) for r in ranks}
    unatt = {r: 0.0 for r in ranks}
    for r in ranks:
        for e in streams[r]:
            for k, v in (e.get("stages") or {}).items():
                if isinstance(v, (int, float)):
                    totals[r][k] += v
            u = e.get("unattributed_s")
            if isinstance(u, (int, float)):
                unatt[r] += u
    stages_seen = [k for k in STAGE_ORDER
                   if any(totals[r].get(k) for r in ranks)]
    if stages_seen:
        print("stage seconds per rank:", file=file)
        for k in stages_seen:
            print(f"  {k:<15s} " + "  ".join(
                f"{_rname(r)}={totals[r].get(k, 0.0):.4f}s" for r in ranks),
                file=file)
        print("  unattributed    " + "  ".join(
            f"{_rname(r)}={unatt[r]:.4f}s" for r in ranks), file=file)


def attrib_report(path: str, events: list, top: int = 10,
                  file=None) -> int:
    """Stage-waterfall view of one trace file (see observe/attrib.py).

    Three blocks: per-program stage decomposition (where each program's
    cumulative wall went), the most recent per-flush waterfalls, and the
    top programs by unattributed gap — wall time none of the stage
    stamps explain (fault injection, GC pauses, lock convoys, ...)."""
    file = file or sys.stdout
    flushes = [e for e in events
               if e.get("type") == "flush" and e.get("stages")]
    print(f"{path}:", file=file)
    if not flushes:
        print("  no stage-attributed flush spans "
              "(trace predates the attribution plane?)", file=file)
        return 1
    per_label: dict = {}
    for e in flushes:
        agg = per_label.setdefault(e.get("label", "?"), {
            "n": 0, "wall": 0.0, "unattributed": 0.0,
            "stages": defaultdict(float),
        })
        agg["n"] += 1
        agg["wall"] += e.get("wall_s") or 0.0
        u = e.get("unattributed_s")
        agg["unattributed"] += u if isinstance(u, (int, float)) else 0.0
        for k, v in e["stages"].items():
            if isinstance(v, (int, float)):
                agg["stages"][k] += v

    def _waterfall(stages: dict, wall: float, unattributed: float) -> str:
        parts = []
        for k in STAGE_ORDER:
            v = stages.get(k)
            if not v:
                continue
            pct = f" {v / wall:.0%}" if wall > 0 else ""
            parts.append(f"{k}={v:.4f}s{pct}")
        if unattributed:
            pct = f" {unattributed / wall:.0%}" if wall > 0 else ""
            parts.append(f"unattributed={unattributed:.4f}s{pct}")
        return "  ".join(parts)

    print(f"stage waterfall ({len(flushes)} attributed flush(es), "
          f"{len(per_label)} program(s)):", file=file)
    ranked = sorted(per_label.items(), key=lambda kv: kv[1]["wall"],
                    reverse=True)
    for label, agg in ranked[:top]:
        print(f"  {label} x{agg['n']} wall={agg['wall']:.4f}s", file=file)
        print("    " + _waterfall(agg["stages"], agg["wall"],
                                  agg["unattributed"]), file=file)
    # plan-cache fast path (PR-18): a hit skips the prepare-side
    # analysis pipeline, so its prepare+verify collapses to the
    # version-vector check — quantify the drop against the miss path
    def _pv(e: dict) -> float:
        s = e["stages"]
        return ((s.get("prepare") or 0.0) + (s.get("verify") or 0.0))

    plan_hits = [e for e in flushes if e.get("plan_cache")]
    if plan_hits:
        plan_misses = [e for e in flushes if not e.get("plan_cache")]
        hs = sorted(_pv(e) for e in plan_hits)
        h50 = hs[len(hs) // 2]
        line = (f"plan-cache fast path: {len(plan_hits)} hit(s)  "
                f"prepare+verify p50 {h50 * 1e6:.0f}us")
        if plan_misses:
            ms = sorted(_pv(e) for e in plan_misses)
            m50 = ms[len(ms) // 2]
            line += f" vs {m50 * 1e6:.0f}us on the miss path"
            if h50 > 0:
                line += f" ({m50 / h50:.1f}x)"
        print(line, file=file)
    # sampled attribution (RAMBA_ATTRIB=sample:<N>): estimated spans
    # carry a rolling fenced p50 instead of a measured device window
    estimated = [e for e in flushes
                 if e.get("device_source") == "estimated"]
    if estimated:
        fenced = sum(1 for e in flushes
                     if e.get("device_source") == "fenced")
        print(f"sampled attribution: {fenced} fenced / "
              f"{len(estimated)} estimated span(s) "
              "(device_est_s = rolling fenced p50)", file=file)
    recent = flushes[-8:]
    print(f"recent flushes (last {len(recent)}):", file=file)
    for e in recent:
        wall = e.get("wall_s") or 0.0
        u = e.get("unattributed_s")
        u = u if isinstance(u, (int, float)) else 0.0
        rung = e.get("degraded", "fused")
        plan = f" plan={e['plan_cache']}" if e.get("plan_cache") else ""
        dev = ""
        if e.get("device_source") == "estimated":
            est = e.get("device_est_s")
            dev = (f" dev~{est:.4f}s(est)"
                   if isinstance(est, (int, float))
                   else " dev=?(est,no fenced history)")
        print(f"  {e.get('label', '?')} [{rung}]{plan} wall={wall:.4f}s{dev}  "
              + _waterfall(e["stages"], wall, u), file=file)
    # incident explainer verdicts (stamped by the sentinels — see
    # observe/attrib.py explain()): why each incident's flush diverged
    whys = [e for e in events if e.get("why")]
    if whys:
        print(f"incident explainer verdicts ({len(whys)}):", file=file)
        for e in whys[-8:]:
            who = e.get("label") or e.get("fingerprint") or ""
            print(f"  {e.get('type', '?'):<16s} {who:<22s} {e['why']}",
                  file=file)
    gaps = sorted(per_label.items(), key=lambda kv: kv[1]["unattributed"],
                  reverse=True)
    gaps = [(lb, a) for lb, a in gaps if a["unattributed"] > 0][:top]
    if gaps:
        print(f"top {len(gaps)} program(s) by unattributed gap:",
              file=file)
        for label, agg in gaps:
            share = (agg["unattributed"] / agg["wall"]
                     if agg["wall"] > 0 else 0.0)
            print(f"  {label:<22s} gap={agg['unattributed']:.4f}s "
                  f"({share:.1%} of {agg['wall']:.4f}s, x{agg['n']})",
                  file=file)
    return 0


def trace_chain(trace_id: str, per_rank: dict, file=None) -> int:
    """Reconstruct ONE request's causal chain across processes.

    Every event stamped with ``trace_id`` (directly, or via the
    ``trace_ids`` list on a coalesced-batch event) is collected from all
    input streams (SPMD ranks, or fleet replicas when the input was a
    directory) and re-threaded by span parentage: the ``serve_session``
    root, then each flush span in time order, with that span's child
    events (degrade rungs, stalls, memory admissions, slow_flush
    verdicts, barrier spans) indented beneath it — the end-to-end story
    of one request, even when its pieces executed on different processes
    and interleaved with thousands of unrelated events.  A child whose
    ``parent_span`` resolves to NO span in the inputs is an orphaned
    half: its other side ran in a process whose trace was not collected
    (or was lost) — flagged explicitly instead of silently filed as
    session-level."""
    file = file or sys.stdout
    evs = []
    for r in sorted(per_rank):
        for e in per_rank[r]:
            if (e.get("trace_id") == trace_id
                    or trace_id in (e.get("trace_ids") or [])):
                evs.append((r, e))
    if not evs:
        print(f"trace {trace_id}: no events found", file=file)
        return 1

    def _key(pair):
        _r, e = pair
        ts = e.get("ts")
        return (ts if isinstance(ts, (int, float)) else 0.0,
                e.get("seq", 0))

    evs.sort(key=_key)
    ranks = sorted({r for r, _ in evs})
    stamps = [e.get("ts") for _, e in evs
              if isinstance(e.get("ts"), (int, float))]
    t0 = min(stamps) if stamps else None

    def rel(e):
        ts = e.get("ts")
        return (f"+{ts - t0:8.3f}s"
                if t0 is not None and isinstance(ts, (int, float))
                else " " * 10)

    roots = [(r, e) for r, e in evs if e.get("type") == "serve_session"]
    spans = [(r, e) for r, e in evs if e.get("type") == "flush"]
    span_ids = {e.get("span_id") for _, e in spans if e.get("span_id")}
    root_ids = {e.get("span_id") for _, e in roots if e.get("span_id")}
    children = defaultdict(list)
    for r, e in evs:
        if e.get("type") in ("serve_session", "flush"):
            continue
        children[e.get("parent_span")].append((r, e))

    names = [_rname(r) for r in ranks]
    print(f"== trace {trace_id}: {len(evs)} events across "
          f"{len(ranks)} process(es) {names} ==", file=file)
    for r, e in roots:
        line = f"session   stream={e.get('stream', '?')}"
        if e.get("tenant"):
            line += f" tenant={e['tenant']}"
        print(f"{rel(e)} {_rname(r)}  {line}", file=file)
    for i, (r, e) in enumerate(spans):
        line = (f"flush #{i}  {e.get('label', '?')}"
                f" rung={e.get('degraded', 'fused')}"
                f" cache={e.get('cache', '?')}")
        if e.get("queue_s") is not None:
            line += f" queue={e['queue_s']}s"
        line += f" wall={e.get('wall_s', 0):.4f}s"
        if e.get("coalesced"):
            line += f" coalesced={e['coalesced']}"
        print(f"{rel(e)} {_rname(r)}  {line}", file=file)
        for cr, c in sorted(children.get(e.get("span_id"), []),
                            key=lambda p: p[1].get("seq", 0)):
            print(f"{rel(c)} {_rname(cr)}    └ {_merge_line(c)}", file=file)
    # events parented by the session root (or nothing resolvable): the
    # slo_breach verdict, coalesce joins, pre-span stalls.  Split by
    # whether the parent actually resolves: parent_span == a session
    # root (or unset) is normal session-level fan-in; a parent id that
    # matches NOTHING in the inputs means the other half of this trace
    # lives in a process we did not collect — an orphaned half.
    session_level = []
    orphaned = []
    # trace_gap markers: the tail-latch buffer (RAMBA_TRACE_SAMPLE)
    # rotated before this trace latched in — events are missing by
    # sampling policy, not by collection failure
    gaps = [(r, e) for r, e in evs if e.get("type") == "trace_gap"]
    gap_dropped = sum(e.get("dropped") or 0 for _, e in gaps)
    for pid, kids in children.items():
        if pid in span_ids:
            continue
        if pid is None or pid in root_ids:
            session_level.extend(
                (cr, c) for cr, c in kids if c.get("type") != "trace_gap")
        else:
            orphaned.extend((pid, cr, c) for cr, c in kids
                            if c.get("type") != "trace_gap")
    if session_level:
        print("session-level events:", file=file)
        for cr, c in sorted(session_level, key=_key):
            print(f"{rel(c)} {_rname(cr)}  {_merge_line(c)}", file=file)
    if gaps:
        print(f"sampling gap: {gap_dropped} event(s) dropped by the "
              "tail-latch buffer before this trace latched in "
              "(RAMBA_TRACE_SAMPLE head sampling — raise "
              "RAMBA_TRACE_SAMPLE fidelity or the buffer bound to keep "
              "longer pre-incident chains)", file=file)
    if orphaned:
        if gaps:
            print(f"sampled-out events ({len(orphaned)}) — parent span "
                  "fell out of the tail-latch buffer (see sampling gap "
                  "above), NOT a missing rank:", file=file)
        else:
            print(f"ORPHANED events ({len(orphaned)}) — parent span not "
                  "in any collected stream (other half of the trace "
                  "missing):", file=file)
        for pid, cr, c in sorted(orphaned, key=lambda t: _key(t[1:])):
            print(f"{rel(c)} {_rname(cr)}  {_merge_line(c)}"
                  f"  [parent_span={pid}]", file=file)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize RAMBA_TRACE JSONL trace files."
    )
    ap.add_argument("paths", nargs="+",
                    help="trace file(s); .rank* siblings auto-discovered")
    ap.add_argument("--top", type=int, default=10,
                    help="programs to list (default 10)")
    ap.add_argument("--merge-ranks", action="store_true",
                    help="interleave per-rank files into one skew-adjusted"
                         " timeline and flag rank divergence")
    ap.add_argument("--merge-cap", type=int, default=80,
                    help="max merged timeline lines (default 80)")
    ap.add_argument("--attrib", action="store_true",
                    help="stage-waterfall view: per-program stage"
                         " decomposition, recent per-flush waterfalls,"
                         " top programs by unattributed gap")
    ap.add_argument("--trace", metavar="ID", default=None,
                    help="reconstruct one request's causal chain: every"
                         " event carrying this trace_id, across ranks,"
                         " threaded session -> flush spans -> rung/stall"
                         "/memory children")
    args = ap.parse_args(argv)

    if args.trace:
        rc = 0
        for p in args.paths:
            per_rank = _load_streams(p)
            if per_rank is None:
                print(f"{p}: no trace file found", file=sys.stderr)
                return 2
            rc = max(rc, trace_chain(args.trace, per_rank))
        return rc

    if args.attrib:
        rc = 0
        files = []
        for p in args.paths:
            found = _discover(p)
            if not found:
                print(f"{p}: no trace file found", file=sys.stderr)
                return 2
            files += [f for f in found if f not in files]
        for f in files:
            rc = max(rc, attrib_report(f, _load(f), top=args.top))
        return rc

    if args.merge_ranks:
        for p in args.paths:
            per_rank = _load_streams(p)
            if per_rank is None:
                print(f"{p}: no trace file found", file=sys.stderr)
                return 2
            merge_report(p, per_rank, cap=args.merge_cap)
        return 0

    files = []
    for p in args.paths:
        found = _discover(p)
        if not found:
            print(f"{p}: no trace file found", file=sys.stderr)
            return 2
        files += [f for f in found if f not in files]

    for f in files:
        report(f, _load(f), top=args.top)
    return 0


if __name__ == "__main__":
    sys.exit(main())
