#!/usr/bin/env python
"""Fleet serving CLI: run a replica server, or drive/inspect a router.

Three modes:

``--replica``
    Serve this process as one fleet replica: bind an authenticated
    ``multiprocessing.connection`` listener on an ephemeral port, export
    the endpoint into the PR-16 snapshot spool (``RAMBA_FLEET_DIR``,
    required so the router can discover it), and print one marker line::

        REPLICA_READY endpoint=127.0.0.1:45123 replica=host-1234-0

    The suite leg and tests parse that line.  Blocks until a
    ``shutdown`` op arrives (or the process is killed — that is the
    failure the router exists to heal).

``--status``
    Build a router over the spool and print its replica table, session
    table and counters as JSON; ``--metrics`` prints the router's
    Prometheus exposition instead.

``--demo N``
    Spawn N replica subprocesses, route a short tenant workload across
    them, print the router stats, and shut the fleet down — a smoke test
    of the whole serving plane in one command.

Environment: ``RAMBA_FLEET_DIR`` (spool = discovery), ``RAMBA_ARTIFACTS``
(shared memo/AOT tier), ``RAMBA_FLEET_AUTHKEY``, ``RAMBA_ROUTER_*``
(timeout / hedge / redirect knobs — see docs/index.md "Fleet serving &
failover").
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def run_replica(args) -> int:
    from ramba_tpu.fleet.replica import ReplicaServer

    server = ReplicaServer(host=args.host, port=args.port)
    print(f"REPLICA_READY endpoint={server.endpoint} "
          f"replica={server.replica}", flush=True)
    server.serve_forever()
    print(f"REPLICA_EXIT replica={server.replica}", flush=True)
    return 0


def run_status(args) -> int:
    from ramba_tpu.fleet.router import Router

    router = Router(fleet_dir=args.fleet_dir)
    if args.metrics:
        sys.stdout.write(router.metrics_text())
        return 0
    json.dump(router.stats(), sys.stdout, indent=2, default=str)
    print()
    return 0


def spawn_replica(env_extra=None, timeout_s: float = 60.0):
    """Spawn one replica subprocess; returns ``(proc, endpoint)`` after
    the READY marker (used by --demo, the suite leg, and tests)."""
    env = dict(os.environ)
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--replica"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    deadline = time.monotonic() + timeout_s
    endpoint = None
    seen = []
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        seen.append(line)
        if line.startswith("REPLICA_READY"):
            endpoint = dict(
                kv.split("=", 1) for kv in line.split()[1:])["endpoint"]
            break
    if endpoint is None:
        proc.kill()
        tail = "".join(seen[-20:]) or "(no output)"
        raise RuntimeError(
            f"replica failed to start; output tail:\n{tail}")
    return proc, endpoint


def run_demo(args) -> int:
    import tempfile

    from ramba_tpu.fleet.router import Router

    base = tempfile.mkdtemp(prefix="ramba-fleet-demo-")
    os.environ["RAMBA_FLEET_DIR"] = os.path.join(base, "spool")
    os.environ["RAMBA_ARTIFACTS"] = os.path.join(base, "artifacts")
    os.environ.setdefault("RAMBA_FLEET_INTERVAL_S", "1")
    os.environ.setdefault("RAMBA_MEMO", "1")
    procs = []
    try:
        endpoints = []
        for _ in range(args.demo):
            proc, ep = spawn_replica()
            procs.append(proc)
            endpoints.append(ep)
        print(f"demo: {len(endpoints)} replica(s): {endpoints}")
        router = Router(endpoints=endpoints)
        for tenant in ("acme", "globex"):
            sid = router.open_session(tenant=tenant)
            router.step(sid, "init", {"name": "x", "shape": [512],
                                      "fill": 2.0})
            for i in range(4):
                router.step(sid, "affine", {"name": "x", "a": 1.01,
                                            "b": float(i)})
            digest = router.step(sid, "digest")["result"]
            print(f"demo: tenant={tenant} sid={sid[:8]} "
                  f"digest={digest[:16]}…")
            router.close_session(sid)
        json.dump(router.stats(), sys.stdout, indent=2, default=str)
        print()
        router.shutdown_fleet()
        return 0
    finally:
        for proc in procs:
            if proc.poll() is None:
                proc.kill()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="ramba_tpu fleet serving plane: replica server + "
                    "router driver")
    ap.add_argument("--replica", action="store_true",
                    help="serve this process as one fleet replica")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="listen port (default: ephemeral)")
    ap.add_argument("--status", action="store_true",
                    help="print the router's fleet view as JSON")
    ap.add_argument("--metrics", action="store_true",
                    help="with --status: Prometheus exposition instead")
    ap.add_argument("--fleet-dir", default=None,
                    help="spool directory (default RAMBA_FLEET_DIR)")
    ap.add_argument("--demo", type=int, metavar="N", default=0,
                    help="spawn N replicas, route a demo workload, stop")
    args = ap.parse_args(argv)

    if args.replica:
        return run_replica(args)
    if args.demo:
        return run_demo(args)
    if args.status or args.metrics:
        return run_status(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
