#!/usr/bin/env python
"""ramba-lint: offline static analysis over RAMBA_TRACE JSONL captures.

Thin wrapper so the linter runs from a checkout without installation::

    python scripts/ramba_lint.py /tmp/trace.jsonl [--strict] [--json]

Equivalent to ``python -m ramba_tpu.analyze``; see that module's help.
"""

from __future__ import annotations

import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from ramba_tpu.analyze.lint import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
