"""Lease-safe Pallas stencil tuning sweep (round-4 verdict #1).

PERF.md puts the stencil at ~460 GB/s net vs the ~800 GB/s HBM bound; the
named lever is Pallas block-height tuning.  This driver:

* probes chip bring-up in a SUBPROCESS with an internal timeout (a wedged
  chip is never touched beyond the probe — round-4 lease postmortem);
* runs ONE configuration per fresh subprocess (the structure-keyed compile
  cache and leftover HBM buffers make in-process config toggling invalid —
  perf-probe methodology, PERF.md);
* sweeps RAMBA_TPU_STENCIL_BH x {auto, 64, 128, 256, 512} plus the XLA
  shifted-slice path (RAMBA_TPU_PALLAS=0) and a bf16-input variant
  (half the HBM traffic) for the roofline picture;
* writes STENCIL_SWEEP_LAST.json and prints the winner.

Usage: python scripts/tpu_stencil_sweep.py   (exit 0 always; status in JSON)
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PROBE_SRC = """
import jax
d = jax.devices()
import jax.numpy as jnp
assert float(jnp.arange(8.0).sum()) == 28.0
print("PROBE_OK", d[0].platform, flush=True)
"""

# One measurement in a fresh process: PRK star-2 at 8192^2, 30-iteration
# chain with a scalar-fetch completion barrier (block_until_ready does not
# synchronize through the remote-dispatch tunnel).
_WORKER_SRC = r"""
import json, os, signal, sys, time

# Internal watchdog BELOW the driver's subprocess timeout: exit cleanly on
# our own so the lease-holding process is never SIGKILLed from outside
# (round-4 postmortem: the relay lease survives SIGKILL and wedges the
# chip for hours).  SIGALRM's handler runs between bytecodes, so it fires
# as soon as any long native call returns.
def _bail(signum, frame):
    print(json.dumps({"error": "internal watchdog expired"}), flush=True)
    sys.exit(3)

signal.signal(signal.SIGALRM, _bail)
signal.alarm(int(os.environ.get("RAMBA_SWEEP_INTERNAL_TIMEOUT", "480")))

sys.path.insert(0, os.environ["RAMBA_SWEEP_REPO"])
import numpy as np
import ramba_tpu as rt

dtype = os.environ.get("RAMBA_SWEEP_DTYPE", "float32")

@rt.stencil
def star2(a):
    return (0.25 * (a[0, 1] + a[0, -1] + a[1, 0] + a[-1, 0])
            + 0.125 * (a[0, 2] + a[0, -2] + a[2, 0] + a[-2, 0]))

sn = 8192
x = rt.fromarray(np.random.RandomState(0).rand(sn, sn).astype(dtype))
rt.sync()
sk = 30

def chain():
    y = x
    for _ in range(sk):
        y = rt.sstencil(star2, y)
    s = rt.sum(y)
    t0 = time.perf_counter()
    float(s)
    return time.perf_counter() - t0

chain()  # compile
wall = min(chain() for _ in range(2)) / sk
mflops = 13 * (sn - 4) * (sn - 4) / wall / 1e6
gbs = 2 * sn * sn * np.dtype(dtype).itemsize / wall / 1e9
print(json.dumps({"per_iter_ms": round(wall * 1e3, 3),
                  "mflops": round(mflops),
                  "gb_per_s": round(gbs, 1)}), flush=True)
"""


def _run(env_extra, timeout_s):
    env = dict(os.environ)
    env["RAMBA_SWEEP_REPO"] = REPO
    # the worker's own watchdog fires well before the external backstop,
    # so a clean in-process exit (lease released) is the normal timeout
    env.setdefault("RAMBA_SWEEP_INTERNAL_TIMEOUT",
                   str(int(max(60, timeout_s - 120))))
    env.update(env_extra)
    try:
        r = subprocess.run(
            [sys.executable, "-c", _WORKER_SRC],
            capture_output=True, text=True, timeout=timeout_s, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"timed out after {timeout_s:.0f}s"}
    for ln in reversed((r.stdout or "").splitlines()):
        try:
            return json.loads(ln)
        except ValueError:
            continue
    tail = ((r.stderr or "") + (r.stdout or "")).strip().splitlines()[-3:]
    return {"error": f"rc={r.returncode} " + " | ".join(tail)[-300:]}


def main() -> int:
    out = {"ok": False, "configs": {}}
    probe_budget = float(os.environ.get("RAMBA_TPU_PROBE_TIMEOUT", "240"))
    per_cfg = float(os.environ.get("RAMBA_SWEEP_CFG_TIMEOUT", "600"))
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=probe_budget,
        )
        plat = next((ln.split()[1] for ln in (r.stdout or "").splitlines()
                     if ln.startswith("PROBE_OK")), None)
    except Exception as e:  # noqa: BLE001
        plat = None
        out["probe_error"] = repr(e)[:200]
    if plat in (None, "cpu"):
        out["error"] = out.get("probe_error", f"probe got {plat!r}")
        return _finish(out)
    out["platform"] = plat

    configs = [
        ("bh_auto", {}),
        ("bh_64", {"RAMBA_TPU_STENCIL_BH": "64"}),
        ("bh_128", {"RAMBA_TPU_STENCIL_BH": "128"}),
        ("bh_256", {"RAMBA_TPU_STENCIL_BH": "256"}),
        ("bh_512", {"RAMBA_TPU_STENCIL_BH": "512"}),
        ("xla_path", {"RAMBA_TPU_PALLAS": "0"}),
        ("bf16_auto", {"RAMBA_SWEEP_DTYPE": "bfloat16"}),
    ]
    for name, env in configs:
        out["configs"][name] = _run(env, per_cfg)
        print(f"{name}: {out['configs'][name]}", file=sys.stderr, flush=True)

    scored = {k: v["mflops"] for k, v in out["configs"].items()
              if "mflops" in v and not k.startswith("bf16")}
    if scored:
        best = max(scored, key=scored.get)
        out["best"] = {"config": best, "mflops": scored[best]}
        out["ok"] = True
    return _finish(out)


def _finish(out) -> int:
    """Every exit path records the run — a stale previous JSON must never
    masquerade as this run's result."""
    out["recorded_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(os.path.join(REPO, "STENCIL_SWEEP_LAST.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
