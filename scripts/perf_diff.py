#!/usr/bin/env python
"""Compare two perf captures and fail on regressions (`ramba-perf`).

Makes the ``BENCH_r*.json`` trajectory machine-checkable: instead of
eyeballing raw stdout tails across TPU windows, diff two captures and
exit nonzero when any kernel (or headline metric) regressed past a
threshold::

    RAMBA_PERF=1 python bench.py > new.json
    python scripts/perf_diff.py BENCH_r07.json new.json --threshold 1.5

Accepted capture formats (auto-detected, mixable):

* ``bench.py`` JSON output with a ``kernels`` section (RAMBA_PERF=1),
* ``diagnostics.dump()`` snapshots (``perf.kernels``),
* a raw ``diagnostics.perf_report()`` / ``observe.ledger.snapshot()``
  dump (top-level ``kernels``).

Per-kernel comparison uses steady-state execution p50 (falling back to
mean when the window is too small), keyed by the ledger's stable kernel
fingerprint — identical programs fingerprint identically across runs and
ranks, so old/new line up without name matching.  Headline bench scalars
(chain wall, stencil MFLOPS, ...) are compared direction-aware when both
captures carry them.

Exit status: 0 no regressions; 1 regressions found; 2 usage/input error.
"""

from __future__ import annotations

import argparse
import json
import sys

# headline bench.py scalars worth gating on, and which direction is good
_METRIC_DIRECTION = {
    "value": "lower",               # chain wall-clock seconds
    "dispatch_floor_ms": "lower",
    "stencil_mflops": "higher",
    "stencil_iter_mflops": "higher",
    "axpy_gb_per_s": "higher",
    "axpy_gb_per_s_net": "higher",
    "bcast_gelems_per_s": "higher",
    "hbm_gb_per_s": "higher",
    "hbm_gb_per_s_net": "higher",
    "hbm_gb_per_s_xla": "higher",       # per-backend (autotune forced runs)
    "hbm_gb_per_s_pallas": "higher",
    "autotune_race_overhead_ms": "lower",
    "matmul_tflops": "higher",
    "serving_flushes_per_s": "higher",
    "serving_p95_flush_ms": "lower",
    "goodput_flushes_per_s": "higher",  # admitted throughput at 3x load
    "p95_admitted_ms": "lower",         # tail of the admitted set in-SLO
    "shed_fail_fast_ms": "lower",       # classified-rejection fast path
    "memo_hit_rate": "higher",          # result-cache dedup (RAMBA_MEMO)
    "serving_dup_execs": "lower",       # duplicates that escaped batch CSE
    "plan_hit_rate": "higher",          # certificate redemptions (PLANCERT)
    "fast_path_floor_us": "lower",      # prepare+verify p50 on plan hits
    "plan_fast_path_speedup": "higher",  # miss/hit prepare+verify p50 ratio
    "observe_events_per_s": "higher",
    "observe_flush_overhead_pct": "lower",
    "observe_scrape_ms": "lower",
    "fleet_snapshot_ms": "lower",       # one spool-document publish
    "router_overhead_ms": "lower",      # per-step router+transport tax
    "cross_replica_aot_hit_rate": "higher",  # shared-tier warm start
    "failover_heal_ms": "lower",        # kill -> redirect -> replay heal
    "coherence_overhead_ms": "lower",   # loopback agreement-round floor
    "reshard_gb_per_s": "higher",       # staged layout-change collectives
    "reshard_peak_live_bytes": "lower",  # ledger peak during the reshard
    "live_reshape_ms": "lower",         # live mesh-reshape rung
    "checkpoint_reshape_ms": "lower",   # drain->checkpoint->resume fallback
    "cold_start_ms": "lower",           # warm-process first-result wall
    "compile_hit_rate": "higher",       # bucketed shape-soak cache hits
    "bucket_pad_waste_frac": "lower",   # zero-padding overhead of pow2
    "attrib_unattributed_frac": "lower",  # waterfall residual share
    "roofline_peak_frac": "higher",     # best kernel's fraction of peak
    "observer_tax_frac": "lower",       # self-metered observability share
    "trace_bytes_per_flush": "lower",   # full-fidelity JSONL lane cost
    "integrity_overhead_frac": "lower",  # digest stamping share of flush wall
    "audit_overhead_ms": "lower",       # per-shadow-audit recompute cost
    "fsck_scan_ms": "lower",            # offline artifact-tier scan wall
}


def load_capture(path: str) -> dict:
    """Load one capture file; returns ``{"kernels": {...}, "metrics":
    {...}}`` (either may be empty)."""
    with open(path) as f:
        text = f.read()
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        # bench stdout may carry non-JSON warm-up lines; take the last
        # parseable line (bench.py prints exactly one JSON object line)
        obj = None
        for line in reversed(text.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
        if obj is None:
            raise ValueError(f"{path}: no JSON object found")
    if not isinstance(obj, dict):
        raise ValueError(f"{path}: expected a JSON object")
    kernels = obj.get("kernels")
    if kernels is None:
        kernels = obj.get("perf", {}).get("kernels", {})
    metrics = {
        k: obj[k] for k in _METRIC_DIRECTION
        if isinstance(obj.get(k), (int, float))
    }
    kind = obj.get("device_kind")
    if kind is None:
        kind = obj.get("attribution", {}).get("device_kind") \
            if isinstance(obj.get("attribution"), dict) else None
    return {"kernels": kernels or {}, "metrics": metrics,
            "device_kind": kind}


def _exec_stat(entry: dict) -> tuple:
    """(representative steady-state seconds, sample count) for a kernel
    entry — p50 when present, else mean over the full history."""
    ex = entry.get("exec") or {}
    count = int(ex.get("count") or 0)
    p50 = ex.get("p50_s")
    if p50 is not None:
        return float(p50), count
    total = ex.get("total_s")
    if count and total is not None:
        return float(total) / count, count
    return 0.0, count


def diff(old: dict, new: dict, threshold: float,
         min_samples: int) -> tuple:
    """Returns (regressions, improvements, skipped) row lists."""
    regressions, improvements, skipped = [], [], []
    shared = sorted(set(old["kernels"]) & set(new["kernels"]))
    for fp in shared:
        o, n = old["kernels"][fp], new["kernels"][fp]
        os_, oc = _exec_stat(o)
        ns_, nc = _exec_stat(n)
        label = n.get("label") or o.get("label") or "?"
        if oc < min_samples or nc < min_samples or os_ <= 0:
            skipped.append((fp, label, f"samples {oc}/{nc}"))
            continue
        ratio = ns_ / os_
        row = (fp, label, os_, ns_, ratio)
        if ratio > threshold:
            regressions.append(row)
        elif ratio < 1.0 / threshold:
            improvements.append(row)
    for key, direction in _METRIC_DIRECTION.items():
        ov, nv = old["metrics"].get(key), new["metrics"].get(key)
        if ov is None or nv is None or ov <= 0 or nv <= 0:
            continue
        ratio = (nv / ov) if direction == "lower" else (ov / nv)
        row = (key, f"metric:{direction}", ov, nv, ratio)
        if ratio > threshold:
            regressions.append(row)
        elif ratio < 1.0 / threshold:
            improvements.append(row)
    return regressions, improvements, skipped


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two ramba perf captures; exit 1 on regression"
    )
    ap.add_argument("old", help="baseline capture (bench JSON / perf dump)")
    ap.add_argument("new", help="candidate capture")
    ap.add_argument("--threshold", type=float, default=1.5,
                    help="regression ratio per kernel/metric (default 1.5)")
    ap.add_argument("--min-samples", type=int, default=3,
                    help="skip kernels with fewer exec samples (default 3)")
    ap.add_argument("--json", action="store_true",
                    help="emit the verdict as one JSON object")
    args = ap.parse_args(argv)
    if args.threshold <= 1.0:
        print("perf_diff: --threshold must be > 1.0", file=sys.stderr)
        return 2
    try:
        old = load_capture(args.old)
        new = load_capture(args.new)
    except (OSError, ValueError) as e:
        print(f"perf_diff: {e}", file=sys.stderr)
        return 2
    if not old["kernels"] and not old["metrics"]:
        print(f"perf_diff: {args.old}: no kernels/metrics section "
              "(run with RAMBA_PERF=1?)", file=sys.stderr)
        return 2
    ok, nk = old.get("device_kind"), new.get("device_kind")
    if ok and nk and ok != nk:
        # different silicon: ratios are apples-to-oranges — warn, don't
        # gate (roofline fractions stay comparable, raw seconds don't)
        print(f"perf_diff: WARNING: device_kind mismatch "
              f"({ok!r} vs {nk!r}) — kernel-time ratios compare "
              "different hardware", file=sys.stderr)
    regressions, improvements, skipped = diff(
        old, new, args.threshold, args.min_samples
    )
    shared = len(set(old["kernels"]) & set(new["kernels"]))
    only_old = len(set(old["kernels"]) - set(new["kernels"]))
    only_new = len(set(new["kernels"]) - set(old["kernels"]))
    if args.json:
        print(json.dumps({
            "threshold": args.threshold,
            "shared_kernels": shared,
            "only_old": only_old, "only_new": only_new,
            "regressions": [
                {"key": k, "label": lb, "old": o, "new": n,
                 "ratio": round(r, 3)}
                for k, lb, o, n, r in regressions
            ],
            "improvements": [
                {"key": k, "label": lb, "old": o, "new": n,
                 "ratio": round(r, 3)}
                for k, lb, o, n, r in improvements
            ],
            "skipped": len(skipped),
            "verdict": "regressed" if regressions else "ok",
        }))
    else:
        print(f"perf_diff: {shared} shared kernel(s), "
              f"{only_old} only in old, {only_new} only in new, "
              f"{len(skipped)} skipped (too few samples)")
        for k, lb, o, n, r in regressions:
            print(f"  REGRESSION {k} {lb}: {o:.6g} -> {n:.6g} "
                  f"({r:.2f}x, threshold {args.threshold}x)")
        for k, lb, o, n, r in improvements:
            print(f"  improved   {k} {lb}: {o:.6g} -> {n:.6g} "
                  f"({1 / r:.2f}x faster)")
        print(f"perf_diff verdict: "
              f"{'REGRESSED' if regressions else 'ok'} "
              f"({len(regressions)} regression(s), "
              f"{len(improvements)} improvement(s))")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
