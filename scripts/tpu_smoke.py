"""Hardware smoke test for the Pallas stencil fast path.

Round-2 postmortem: the fast kernel was only ever exercised in interpret
mode, so a Mosaic compile failure ("tile index in dimension 0 … divisible
by the tiling (8)" at the 8192x8192 bench shape) survived two rounds of
green tests.  This script compiles and runs the kernel on the real chip at
the shapes that matter — including the exact bench shape — and checks
numerics against the XLA shifted-slice path.

Run directly (exit code 0 = all shapes pass), or import `smoke()` from
bench.py as a pre-flight gate.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def smoke(shapes=((1024, 1024), (8192, 8192)), verbose=True) -> list:
    """Compile + run the stencil fast path at each shape; return failures."""
    import jax

    import ramba_tpu as rt
    from ramba_tpu.ops import stencil_pallas

    @rt.stencil
    def star2(a):
        return (
            0.25 * (a[0, 1] + a[0, -1] + a[1, 0] + a[-1, 0])
            + 0.125 * (a[0, 2] + a[0, -2] + a[2, 0] + a[-2, 0])
        )

    failures = []
    for shape in shapes:
        try:
            rng = np.random.RandomState(0)
            xa = rng.rand(*shape).astype(np.float32)
            x = rt.fromarray(xa)
            y = rt.sstencil(star2, x)
            got = np.asarray(y)
            # spot-check numerics on a small patch against pure NumPy
            r, c = 4, 4
            want = (
                0.25 * (xa[r, c + 1] + xa[r, c - 1] + xa[r + 1, c] + xa[r - 1, c])
                + 0.125 * (xa[r, c + 2] + xa[r, c - 2] + xa[r + 2, c] + xa[r - 2, c])
            )
            assert abs(got[r, c] - want) < 1e-4, (got[r, c], want)
            assert np.all(got[:2, :] == 0) and np.all(got[:, :2] == 0)
            if verbose:
                print(f"smoke {shape}: ok (pallas_used="
                      f"{stencil_pallas.available([x._value()])})")
        except Exception as e:  # noqa: BLE001 - report, don't die
            failures.append((shape, repr(e)))
            if verbose:
                print(f"smoke {shape}: FAIL {e!r}", file=sys.stderr)
    return failures


if __name__ == "__main__":
    fails = smoke()
    sys.exit(1 if fails else 0)
