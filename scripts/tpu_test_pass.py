"""On-hardware test pass (round-4 verdict #7).

Runs a tagged subset of the test suite on the real TPU chip (1-device mesh,
x32 regime) and records ``TESTS_TPU_LAST.json`` at the repo root — the same
carry-forward pattern as bench.py's BENCH_TPU_LAST.json, so hardware test
evidence survives chip outages.

Lease-safety (round-4 postmortem: killing a client that holds the axon
relay lease wedges the chip for hours):

* bring-up is probed in a SUBPROCESS with an internal timeout first — if
  the chip is wedged, nothing else ever touches it;
* the pytest run itself gets an internal ``timeout`` budget and exits
  cleanly on its own; run this script via ``timeout <big>`` only.

The reference's CI runs its whole suite on the same backend users run
(/root/reference/.github/workflows/python-package.yml:40-46, CPU
everywhere); the rebuild's CPU-mesh legs cover breadth, and this pass
covers the "same numerics on the real chip" leg.

Usage: python scripts/tpu_test_pass.py  [--files f1 f2 ...]
Exit code 0 always (status is in the JSON on stdout).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# ~120 tests spanning dtype promotion, reductions, skeletons (smap/sreduce/
# scumulative/spmd), fusion/segmentation, and both stencil paths — the
# subset named by the round-4 verdict, kept 1-device-safe.
DEFAULT_FILES = [
    "tests/test_skeletons.py",
    "tests/test_fusion.py",
    "tests/test_pallas_stencil.py",
    "tests/test_sharded_stencil.py",
]

_PROBE_SRC = """
import jax
d = jax.devices()
import jax.numpy as jnp
assert float(jnp.arange(8.0).sum()) == 28.0
print("PROBE_OK", d[0].platform, flush=True)
"""


def probe(timeout_s: float):
    try:
        r = subprocess.run(
            [sys.executable, "-c", _PROBE_SRC],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return None, f"probe: timed out after {timeout_s:.0f}s"
    except Exception as e:  # noqa: BLE001
        return None, f"probe: {e!r}"
    for ln in (r.stdout or "").splitlines():
        if ln.startswith("PROBE_OK"):
            return ln.split()[1], None
    tail = ((r.stderr or "") + (r.stdout or "")).strip().splitlines()[-3:]
    return None, f"probe: rc={r.returncode} " + " | ".join(tail)[-300:]


def main() -> int:
    out = {"ok": False, "platform": None}
    files = DEFAULT_FILES
    if "--files" in sys.argv:
        files = sys.argv[sys.argv.index("--files") + 1:]
    probe_budget = float(os.environ.get("RAMBA_TPU_PROBE_TIMEOUT", "240"))
    run_budget = float(os.environ.get("RAMBA_TPU_TESTS_TIMEOUT", "3000"))

    plat, err = probe(probe_budget)
    if plat is None or plat == "cpu":
        out["error"] = err or f"probe selected {plat}, not hardware"
        print(json.dumps(out))
        return 0
    out["platform"] = plat

    env = dict(os.environ)
    env["RAMBA_TEST_TPU"] = "1"
    # the virtual-device flag is CPU-only, but keep the env clean anyway
    env.pop("XLA_FLAGS", None)
    t0 = time.time()
    try:
        r = subprocess.run(
            [sys.executable, "-m", "pytest", "-q", "--tb=line",
             "-p", "no:cacheprovider", *files],
            capture_output=True, text=True, timeout=run_budget, cwd=REPO,
            env=env,
        )
    except subprocess.TimeoutExpired as e:
        out["error"] = f"pytest: timed out after {run_budget:.0f}s"
        tail = (e.stdout or b"")
        if isinstance(tail, bytes):
            tail = tail.decode(errors="replace")
        out["stdout_tail"] = tail[-1500:]
        print(json.dumps(out))
        return 0
    out["duration_s"] = round(time.time() - t0, 1)
    out["rc"] = r.returncode
    lines = (r.stdout or "").splitlines()
    # pytest -q summary: "N passed, M skipped in Xs" / "K failed, ..."
    summary = next((ln for ln in reversed(lines)
                    if " in " in ln and ("passed" in ln or "failed" in ln
                                         or "error" in ln)), "")
    out["summary"] = summary.strip("= ")
    import re

    for key in ("passed", "failed", "skipped", "errors"):
        m = re.search(rf"(\d+) {key.rstrip('s')}", summary)
        out[key] = int(m.group(1)) if m else 0
    out["failures"] = [ln for ln in lines if ln.startswith("FAILED")][:15]
    out["files"] = files
    out["ok"] = r.returncode == 0 and out["passed"] > 0
    out["recorded_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(os.path.join(REPO, "TESTS_TPU_LAST.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
