#!/usr/bin/env python
"""Fleet collector CLI: read a RAMBA_FLEET_DIR snapshot spool and report.

Each ramba_tpu process with ``RAMBA_FLEET_DIR`` set publishes an atomic
versioned snapshot of its full diagnostics state every
``RAMBA_FLEET_INTERVAL_S`` seconds (ramba_tpu/observe/fleet.py).  This
CLI is the reader side — run it anywhere the spool directory is visible
(NFS mount, rsync target, the host itself); it never initializes an
accelerator backend (JAX_PLATFORMS defaults to cpu below).

Usage:
    python scripts/fleet_collector.py /srv/ramba-fleet
    python scripts/fleet_collector.py /srv/ramba-fleet --json
    python scripts/fleet_collector.py /srv/ramba-fleet --prom -
    python scripts/fleet_collector.py /srv/ramba-fleet \
        --prom /var/lib/node_exporter/ramba_fleet.prom --watch 10

One-shot by default: prints the replica health table (state, reason,
snapshot age, publish seq) and the fleet rollup (merged per-tenant SLO
percentiles, goodput totals with per-replica rows, cache hit-rate
comparison, worst rooflines).  ``--json`` emits the same as one JSON
object.  ``--prom PATH`` writes the fleet Prometheus textfile atomically
(``-`` prints the exposition to stdout).  ``--watch N`` repeats every N
seconds until interrupted — the poor operator's dashboard.

Exit status encodes the fleet verdict for scripting: 0 all-healthy,
1 degraded, 2 stale, 3 dead replicas present, 4 empty/missing spool.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# reader-side process: never let the collector grab an accelerator
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from ramba_tpu.observe import fleet  # noqa: E402

_EXIT = {fleet.HEALTHY: 0, fleet.DEGRADED: 1, fleet.STALE: 2, fleet.DEAD: 3}


def _pct(v):
    return "-" if v is None else f"{v:.1f}ms"


def print_report(directory: str, file=None, polled=None) -> int:
    file = file or sys.stdout
    # one spool read per tick: health and rollup come from the same
    # fleet.poll() pass the router consumes, so the two cannot drift
    polled = polled or fleet.poll(directory)
    h = polled["health"]
    print(f"== fleet {directory} ({len(h['replicas'])} replica(s), "
          f"fleet_state={h['fleet_state']}) ==", file=file)
    if not h["replicas"]:
        print("no spool documents found", file=file)
        return 4
    print(f"  {'replica':<32s} {'state':<9s} {'age':>8s} {'seq':>6s}  reason",
          file=file)
    order = {s: i for i, s in enumerate(fleet._SEVERITY)}
    for rep, row in sorted(h["replicas"].items(),
                           key=lambda kv: (order[kv[1]["state"]], kv[0])):
        age = "-" if row["age_s"] is None else f"{row['age_s']:.1f}s"
        seq = "-" if row["publish_seq"] is None else str(row["publish_seq"])
        print(f"  {rep:<32s} {row['state']:<9s} {age:>8s} {seq:>6s}  "
              f"{row['reason']}", file=file)

    roll = polled["rollup"]
    gp = roll["goodput"]
    print(f"goodput (over {len(roll['replicas'])} fresh replica(s)): "
          f"flushes={gp['flushes']} nodes={gp['nodes_flushed']} "
          f"serve={gp['serve_flushes']} shed={gp['shed_total']} "
          f"slo_breaches={gp['slo_breaches']}", file=file)
    for rep, row in sorted(gp["replicas"].items()):
        up = "-" if row["uptime_s"] is None else f"{row['uptime_s']:.0f}s"
        print(f"  {rep:<32s} flushes={row['flushes']:<8d} "
              f"shed={row['shed_total']:<6d} uptime={up}", file=file)
    for metric, tenants in sorted(roll["slo"].items()):
        for tenant, summ in sorted(tenants.items()):
            print(f"slo {metric} tenant={tenant or '(default)'}: "
                  f"n={summ.get('count', 0)} "
                  f"p50={_pct(summ.get('p50_ms'))} "
                  f"p95={_pct(summ.get('p95_ms'))} "
                  f"p99={_pct(summ.get('p99_ms'))}", file=file)
    if roll["caches"]:
        print("caches (jit / memo / AOT):", file=file)
        for rep, row in sorted(roll["caches"].items()):
            jit = ("-" if row["jit_hit_rate"] is None
                   else f"{row['jit_hit_rate']:.0%}")
            memo = ("-" if row["memo_hit_rate"] is None
                    else f"{row['memo_hit_rate']:.0%}")
            print(f"  {rep:<32s} jit={jit:<5s} memo={memo:<5s} "
                  f"aot={row['aot_hits']}/{row['aot_hits'] + row['aot_misses']}",
                  file=file)
    for r in roll["rooflines"][:8]:
        print(f"roofline {r['label']:<18s} {r['bound']}-bound "
              f"{r['frac_of_peak']:.1%} of peak  "
              f"replica={r['replica']}", file=file)
    return _EXIT[h["fleet_state"]]


def run_once(args) -> int:
    polled = fleet.poll(args.fleet_dir)
    if args.json:
        out = {"health": polled["health"], "rollup": polled["rollup"]}
        json.dump(out, sys.stdout, indent=2, default=str)
        print()
        rc = (_EXIT[out["health"]["fleet_state"]]
              if out["health"]["replicas"] else 4)
    elif args.prom and not args.prom_also_report:
        rc = _EXIT[polled["health"]["fleet_state"]]
    else:
        rc = print_report(args.fleet_dir, polled=polled)
    if args.prom == "-":
        sys.stdout.write(fleet.render(args.fleet_dir))
    elif args.prom:
        fleet.write_textfile(args.prom, args.fleet_dir)
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Collect and report a ramba_tpu fleet snapshot spool."
    )
    ap.add_argument("fleet_dir", help="spool directory (RAMBA_FLEET_DIR)")
    ap.add_argument("--json", action="store_true",
                    help="emit health + rollup as one JSON object")
    ap.add_argument("--prom", metavar="PATH", default=None,
                    help="write the fleet Prometheus textfile atomically"
                         " ('-' prints the exposition to stdout)")
    ap.add_argument("--prom-also-report", action="store_true",
                    help="with --prom PATH, also print the human report")
    ap.add_argument("--watch", type=float, metavar="N", default=None,
                    help="repeat every N seconds until interrupted")
    args = ap.parse_args(argv)

    if args.watch:
        rc = 0
        try:
            while True:
                rc = run_once(args)
                time.sleep(max(0.1, args.watch))
        except KeyboardInterrupt:
            return rc
    return run_once(args)


if __name__ == "__main__":
    sys.exit(main())
