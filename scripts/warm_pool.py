#!/usr/bin/env python
"""Replay a RAMBA_TRACE capture's hottest programs through the compile
pipeline before opening to traffic — the operational wrapper around
``ramba_tpu.compile.warmpool``.

    # yesterday's shift recorded a trace; warm tomorrow's process:
    RAMBA_CACHE=/var/cache/ramba python scripts/warm_pool.py \
        --trace /var/log/ramba/trace.jsonl --top-k 8

The trace's ``program`` events (which carry kernel fingerprint and
compile class since PR 14) are ranked by arrival count, re-weighted by
the live ledger when one exists, resolved against the persist cache's
program skeletons, and submitted through ``CompilePipeline.submit_warm``
— so warm compiles take round-robin turns with live traffic and are the
first load shed under brownout (``serve.warm_shed``).  Exit status is 0
even when individual warm-ups fail: a failed pre-compile is a lost
opportunity, not an error.
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", required=True,
                    help="RAMBA_TRACE JSONL capture to rank programs from")
    ap.add_argument("--top-k", type=int, default=8,
                    help="warm at most this many (fingerprint, class) "
                         "pairs (default 8)")
    ap.add_argument("--budget-s", type=float, default=None,
                    help="stop submitting after this many seconds")
    ap.add_argument("--timeout", type=float, default=120.0,
                    help="per-ticket wait timeout in seconds")
    ap.add_argument("--json", action="store_true",
                    help="print the report as one JSON line")
    args = ap.parse_args(argv)

    from ramba_tpu import common
    from ramba_tpu.compile import persist as _persist
    from ramba_tpu.compile import warmpool as _warmpool

    common.setup_persistent_cache()
    _persist.reconfigure()
    if not _persist.armed():
        print("warm_pool: persist cache not armed (set RAMBA_CACHE); "
              "nothing to replay", file=sys.stderr)
        return 1

    report = _warmpool.warm(args.trace, top_k=args.top_k,
                            budget_s=args.budget_s, timeout=args.timeout)
    if args.json:
        print(json.dumps(report))
    else:
        print("warm_pool: "
              f"considered={report['considered']} "
              f"submitted={report['submitted']} warmed={report['warmed']} "
              f"failed={report['failed']} shed={report['shed']} "
              f"unresolved={report['unresolved']} "
              f"seconds={report['seconds']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
