"""Two-process multi-controller smoke test (CPU backend).

The reference CI runs its whole suite on a 2-worker cluster (mpiexec -n 2,
/root/reference/.github/workflows/python-package.yml:40-46).  The TPU-native
equivalent of that mode is jax multi-controller SPMD: every process runs the
same program, `jax.distributed.initialize` forms the process group, and the
global mesh spans both processes' devices (parallel/distributed.py).

Run with no arguments to launch the 2-process test (exit 0 = pass):

    python scripts/two_process_smoke.py

Each worker: initializes the group, builds the cross-process global mesh,
creates a sharded array, runs a cross-process all-reduce via rt.sum, an
elementwise chain, and checks in_driver() gating.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def worker(rank: int, port: int) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 2)

    sys.path.insert(0, REPO)
    from ramba_tpu.parallel import distributed

    distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=2,
        process_id=rank,
    )
    assert jax.process_count() == 2, jax.process_count()
    assert distributed.process_index() == rank
    assert len(jax.devices()) == 4, jax.devices()
    assert len(distributed.local_devices()) == 2

    import ramba_tpu as rt

    mesh = distributed.global_mesh()
    assert mesh.devices.size == 4
    rt.set_mesh(mesh)

    # sharded creation + fused chain + global reduction (the all-reduce
    # crosses the process boundary)
    n = 1 << 12
    a = rt.arange(n, dtype=float)
    d = rt.sin(a) * rt.sin(a) + rt.cos(a) ** 2
    total = float(rt.sum(d))
    assert abs(total - n) < 1e-6 * n, total

    s = float(rt.sum(a))
    assert s == n * (n - 1) / 2, s

    # sharded-directory save/load across the process boundary: each
    # process writes its own shards + manifest (synchronous host writes),
    # a collective acts as the barrier, then both reassemble the array
    rtd = os.environ["RAMBA_TPU_SMOKE_RTD"]
    big = rt.arange(n, dtype=float) * 3.0
    rt.save(rtd, big)
    float(rt.sum(rt.ones(256)))  # collective: all shards written
    back = rt.load(rtd)
    diff = float(rt.sum((back - big) * (back - big)))
    assert diff == 0.0, diff

    # single-file save under multi-controller: all-gather -> driver rank
    # writes -> barrier (round-4 verdict #4 follow-on; used to refuse)
    npy = os.path.join(os.path.dirname(rtd), "single.npy")
    rt.save(npy, big)
    back1 = rt.load(npy)
    diff1 = float(rt.sum((back1 - big) * (back1 - big)))
    assert diff1 == 0.0, diff1

    # the skeleton surface across the process boundary (round 4): a
    # 3-point spmd halo sweep — the ppermute crosses processes — and a
    # fori_loop stencil; verification is by collective checksum (a global
    # array is not fully addressable from one controller)
    import numpy as np

    v = np.arange(float(n))
    src = rt.arange(n, dtype=float)
    out = rt.zeros(n)
    rt.sync()

    def sweep(s_, d_):
        h = s_.halo(1)
        d_.set_local(h[:-2] + h[1:-1] + h[2:])

    rt.spmd(sweep, src, out)
    exp = np.zeros(n)
    exp[1:-1] = v[:-2] + v[1:-1] + v[2:]
    exp[0] = v[0] + v[1]
    exp[-1] = v[-2] + v[-1]
    got = float(rt.sum(out * out))
    want = float((exp * exp).sum())
    # f32 regime: the checksum accumulates 4096 terms of ~1e8
    assert abs(got - want) <= 1e-5 * max(1.0, abs(want)), (got, want)

    @rt.stencil
    def avg3(a):
        return (a[-1] + a[0] + a[1]) / 3.0

    it = rt.sstencil_iterate(avg3, src, 3)
    e = v.copy()
    for _ in range(3):
        nxt = np.zeros_like(e)
        nxt[1:-1] = (e[:-2] + e[1:-1] + e[2:]) / 3.0
        e = nxt
    got = float(rt.sum(it))
    want = float(e.sum())
    assert abs(got - want) <= 1e-5 * max(1.0, abs(want)), (got, want)

    # driver gating (reference: in_driver() in MPI SPMD mode)
    if distributed.in_driver():
        assert rank == 0
        print("DRIVER_OK", flush=True)
    else:
        assert rank == 1
    print(f"WORKER_{rank}_OK", flush=True)
    distributed.shutdown()


def launch() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    import tempfile

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO  # drop site hooks that force a TPU backend
    env.pop("JAX_PLATFORMS", None)
    env["RAMBA_TPU_SMOKE_RTD"] = os.path.join(
        tempfile.mkdtemp(prefix="rtd_smoke_"), "arr.rtd"
    )
    procs = [
        subprocess.Popen(
            [sys.executable, "-u", os.path.abspath(__file__),
             "WORKER", str(rank), str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True,
        )
        for rank in range(2)
    ]
    ok = True
    for rank, p in enumerate(procs):
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            p.kill()
            out = f"rank {rank}: TIMEOUT"
        if p.returncode != 0 or f"WORKER_{rank}_OK" not in (out or ""):
            ok = False
            print(f"--- rank {rank} rc={p.returncode} ---\n{out}",
                  file=sys.stderr)
    if ok:
        print("two-process smoke: OK")
    return 0 if ok else 1


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "WORKER":
        worker(int(sys.argv[2]), int(sys.argv[3]))
    else:
        sys.exit(launch())
