"""Round-4 perf probes on the live chip (see PERF.md "Measured on
hardware"): separate framework overhead from XLA/hardware limits for the
three bench sections below their rooflines.

Run:  python scripts/perf_probe.py [chain|axpy|stencil|all]
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _t(fn, reps=3):
    fn()  # compile/warm
    return min(_t1(fn) for _ in range(reps))


def _t1(fn):
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def probe_dispatch_floor(rt, jnp, jax):
    small = rt.fromarray(np.ones(8, np.float32))
    rt.sync()

    def f():
        float(rt.sum(small))

    w = _t(f, 5)
    print(f"dispatch floor (flush+fetch tiny): {w*1e3:.2f} ms")

    xs = jnp.ones(8)
    xs.block_until_ready()
    g = jax.jit(jnp.sum)

    def f2():
        float(g(xs))

    w2 = _t(f2, 5)
    print(f"raw jit dispatch floor:            {w2*1e3:.2f} ms")


def probe_chain(rt, jnp, jax, n):
    def rt_chain():
        A = rt.arange(n) / 1000.0
        B = rt.sin(A)
        C = rt.cos(A)
        D = B * B + C ** 2
        del A, B, C
        float(rt.sum(D))

    w = _t(rt_chain)
    print(f"rt chain n={n:.0e}: {w*1e3:.1f} ms ({4*n/1e9/w:.1f} GB/s eff)")

    @jax.jit
    def pure(n_):
        A = jnp.arange(n, dtype=jnp.float32) / 1000.0
        B = jnp.sin(A)
        C = jnp.cos(A)
        D = B * B + C ** 2
        return D, jnp.sum(D)

    def jnp_chain():
        d, s = pure(0)
        float(s)
        d.delete()

    w2 = _t(jnp_chain)
    print(f"jnp chain n={n:.0e}: {w2*1e3:.1f} ms ({4*n/1e9/w2:.1f} GB/s eff)")

    # transcendental cost isolation: same traffic, no sin/cos
    @jax.jit
    def poly(n_):
        A = jnp.arange(n, dtype=jnp.float32) / 1000.0
        D = A * A + A + 1.0
        return D, jnp.sum(D)

    def jnp_poly():
        d, s = poly(0)
        float(s)
        d.delete()

    w3 = _t(jnp_poly)
    print(f"jnp poly  n={n:.0e}: {w3*1e3:.1f} ms ({4*n/1e9/w3:.1f} GB/s eff)")


def probe_axpy(rt, jnp, jax):
    for n in (100_000_000, 400_000_000):
        x = rt.random.normal(size=n)
        y = rt.random.normal(size=n)
        rt.sync()

        def run():
            z = 2.5 * x + y
            float(rt.sum(z))

        w = _t(run)
        print(f"rt axpy n={n:.0e}: {w*1e3:.2f} ms ({3*n*4/1e9/w:.1f} GB/s)")

    n = 400_000_000
    xj = jnp.asarray(np.random.rand(n).astype(np.float32))
    yj = jnp.asarray(np.random.rand(n).astype(np.float32))
    xj.block_until_ready(); yj.block_until_ready()

    @jax.jit
    def ax(x_, y_):
        z = 2.5 * x_ + y_
        return z, jnp.sum(z)

    def run2():
        z, s = ax(xj, yj)
        float(s)
        z.delete()

    w = _t(run2)
    print(f"jnp axpy n={n:.0e}: {w*1e3:.2f} ms ({3*n*4/1e9/w:.1f} GB/s)")


def probe_stencil(rt, jnp, jax):
    from ramba_tpu.ops import stencil_pallas

    sn = 8192
    x = rt.fromarray(np.random.RandomState(0).rand(sn, sn).astype(np.float32))
    rt.sync()

    @rt.stencil
    def star2(a):
        return (
            0.25 * (a[0, 1] + a[0, -1] + a[1, 0] + a[-1, 0])
            + 0.125 * (a[0, 2] + a[0, -2] + a[2, 0] + a[-2, 0])
        )

    def chain(k):
        def f():
            y = x
            for _ in range(k):
                y = rt.sstencil(star2, y)
            float(rt.sum(y))
        return f

    for label, enabled, bh in (
        ("pallas auto-bh", True, 0),
        ("pallas bh=128", True, 128),
        ("pallas bh=256", True, 256),
        ("pallas bh=512", True, 512),
        ("pallas bh=1024", True, 1024),
        ("xla shifted-slice", False, 0),
    ):
        stencil_pallas._ENABLED = enabled
        stencil_pallas._BH = bh
        try:
            w = _t(chain(10), 2) / 10
            print(f"stencil {label}: {w*1e3:.2f} ms/iter "
                  f"({13*(sn-4)**2/w/1e9:.0f} GFlops, "
                  f"{2*sn*sn*4/1e9/w:.0f} GB/s)")
        except Exception as e:  # noqa: BLE001
            print(f"stencil {label}: FAILED {type(e).__name__}: {e}")
    stencil_pallas._ENABLED = True
    stencil_pallas._BH = 0

    # pure-XLA reference: same star2 as shifted slices, k iters in one jit
    xj = jnp.asarray(np.random.rand(sn, sn).astype(np.float32))
    xj.block_until_ready()

    @jax.jit
    def sweep(a):
        def one(a, _):
            out = (
                0.25 * (jnp.roll(a, -1, 1) + jnp.roll(a, 1, 1)
                        + jnp.roll(a, -1, 0) + jnp.roll(a, 1, 0))
                + 0.125 * (jnp.roll(a, -2, 1) + jnp.roll(a, 2, 1)
                           + jnp.roll(a, -2, 0) + jnp.roll(a, 2, 0))
            )
            return out, None
        a, _ = jax.lax.scan(one, a, None, length=10)
        return a, jnp.sum(a)

    def run():
        a, s = sweep(xj)
        float(s)
        a.delete()

    w = _t(run, 2) / 10
    print(f"jnp roll-stencil (scan x10 in-jit): {w*1e3:.2f} ms/iter "
          f"({13*(sn-4)**2/w/1e9:.0f} GFlops, {2*sn*sn*4/1e9/w:.0f} GB/s)")


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    import jax
    import jax.numpy as jnp

    import ramba_tpu as rt

    print("platform:", jax.devices()[0].platform)
    probe_dispatch_floor(rt, jnp, jax)
    if which in ("chain", "all"):
        probe_chain(rt, jnp, jax, 1_000_000_000)
    if which in ("axpy", "all"):
        probe_axpy(rt, jnp, jax)
    if which in ("stencil", "all"):
        probe_stencil(rt, jnp, jax)


if __name__ == "__main__":
    main()
