#!/usr/bin/env python
"""ramba-fsck: offline integrity verification of everything ramba_tpu
persists — run it before trusting a warm cache tier, after a machine
came back from a crash, or from cron as a corruption tripwire.

What gets scanned (each an independent leg; a leg with nothing to scan
is skipped, and scanning *nothing at all* is its own exit code so a
misconfigured cron job cannot masquerade as a clean fleet):

* the shared artifact tier (``--artifacts`` / ``RAMBA_ARTIFACTS``):
  memo blobs (``memo/*.npz``), plan certificates (``plancert/*.json``),
  migration handoffs (``handoff/*.manifest.json`` + each checkpoint's
  payload byte census + digest sidecar);
* the persistent executable cache (``--cache`` / ``RAMBA_CACHE``):
  AOT entries (``aot/*.aot``) and program skeletons
  (``programs/*.pkl``);
* checkpoint trees (``--checkpoint PATH``, repeatable): the
  ``<path>.digests.json`` sidecar's file map re-verified byte-for-byte,
  elastic ``MANIFEST.json`` self-digests, recursing over
  ``step_<n>/`` layouts.

Verification uses :func:`ramba_tpu.resilience.integrity.verify_blob`,
which never emits events and never strikes the live suspect window —
an offline scan must not quarantine the process running it.

``--repair`` moves every corrupt entry into a ``quarantine/`` directory
beside its scan root (cache entries are disposable: the runtime
recomputes/recompiles on the resulting miss; a quarantined checkpoint
leaf makes the checkpoint refuse restore loudly instead of serving
silently corrupt state).

Exit status (the contract scripts/lint.sh and cron wrappers consume,
mirroring scripts/fleet_collector.py): ``0`` everything verified,
``1`` corruption found (fix or re-run with ``--repair``), ``4``
nothing to scan anywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

from ramba_tpu.resilience import integrity as _integrity  # noqa: E402

#: schema tag per scanned blob shape (import-light: the tags are data,
#: re-declared here so fsck never imports jax through the cache modules)
_MEMO_SCHEMA = "memo.npz"
_CERT_SCHEMA = "plancert.json"
_AOT_SCHEMA = "aot.pkl"
_PROGRAM_SCHEMA = "program.pkl"
_DIGESTS_SCHEMA = "ckpt.digests.json"
_DIGESTS_SUFFIX = ".digests.json"

EXIT_CLEAN = 0
EXIT_CORRUPT = 1
EXIT_EMPTY = 4


def _read(path: str) -> Optional[bytes]:
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return None


def _quarantine(root: str, path: str, report: dict) -> None:
    """Move one corrupt entry into ``<root>/quarantine/``, keeping the
    relative layout so an operator can inspect what was pulled."""
    import shutil

    qdir = os.path.join(root, "quarantine")
    rel = os.path.relpath(path, root)
    if rel.startswith(".."):
        rel = os.path.basename(path)
    dest = os.path.join(qdir, rel)
    try:
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        shutil.move(path, dest)
        report["quarantined"].append({"path": path, "to": dest})
    except OSError as e:
        report["repair_errors"].append({"path": path, "error": str(e)})


def _bad(report: dict, root: str, path: str, schema: str, reason: str,
         repair: bool) -> None:
    report["corrupt"].append({"path": path, "schema": schema,
                              "reason": reason})
    if repair:
        _quarantine(root, path, report)


def _scan_blob_dir(report: dict, root: str, sub: str, suffix: str,
                   schema: str, repair: bool) -> None:
    d = os.path.join(root, sub)
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return
    for name in names:
        if not name.endswith(suffix) or name.startswith(".tmp-"):
            continue
        path = os.path.join(d, name)
        report["scanned"] += 1
        reason = _integrity.verify_blob(_read(path), schema)
        if reason is not None:
            _bad(report, root, path, schema, reason, repair)


def _payload_census(ckpt_dir: str) -> tuple:
    """(total_bytes, sorted file list) over one checkpoint directory —
    the same census fleet/migrate.py records as ``payload_bytes``."""
    files: List[str] = []
    total = 0
    for r, _dirs, names in os.walk(ckpt_dir):
        for name in names:
            full = os.path.join(r, name)
            files.append(full)
            try:
                total += os.path.getsize(full)
            except OSError:
                pass
    return total, sorted(files)


def _scan_sidecar(report: dict, root: str, side: str, repair: bool) -> None:
    """Verify one checkpoint digest sidecar: the sidecar's own envelope,
    then every file it stamps, byte-for-byte."""
    apath = side[:-len(_DIGESTS_SUFFIX)]
    report["scanned"] += 1
    raw = _read(side)
    reason = _integrity.verify_blob(raw, _DIGESTS_SCHEMA)
    if reason is not None:
        _bad(report, root, side, _DIGESTS_SCHEMA, reason, repair)
        return
    try:
        doc = json.loads(raw[raw.index(b"\n") + 1:])
        files = doc.get("files") or {}
    except (ValueError, AttributeError):
        _bad(report, root, side, _DIGESTS_SCHEMA, "deserialize", repair)
        return
    for rel, want in sorted(files.items()):
        full = os.path.join(apath, rel)
        report["scanned"] += 1
        try:
            size = os.path.getsize(full)
        except OSError:
            _bad(report, root, full, "checkpoint:leaf", "missing", repair)
            continue
        if size != want.get("size"):
            _bad(report, root, full, "checkpoint:leaf",
                 f"length:{size}!={want.get('size')}", repair)
            continue
        if _integrity.file_digest(full) != want.get("sha256"):
            _bad(report, root, full, "checkpoint:leaf", "digest", repair)


def _scan_handoffs(report: dict, root: str, repair: bool) -> None:
    d = os.path.join(root, "handoff")
    try:
        names = sorted(os.listdir(d))
    except OSError:
        return
    for name in names:
        if not name.endswith(".manifest.json"):
            continue
        mpath = os.path.join(d, name)
        report["scanned"] += 1
        try:
            man = json.loads(_read(mpath) or b"")
        except ValueError:
            _bad(report, root, mpath, "handoff.manifest", "deserialize",
                 repair)
            continue
        sid = name[:-len(".manifest.json")]
        ckpt = os.path.join(d, sid)
        want = man.get("payload_bytes")
        if want is not None and os.path.isdir(ckpt):
            got, _files = _payload_census(ckpt)
            if got != want:
                _bad(report, root, mpath, "handoff.manifest",
                     f"payload_bytes:{got}!={want}", repair)
        side = ckpt + _DIGESTS_SUFFIX
        if os.path.exists(side):
            _scan_sidecar(report, root, side, repair)


def _scan_manifest_selfdigest(report: dict, root: str, mpath: str,
                              repair: bool) -> None:
    import hashlib

    report["scanned"] += 1
    try:
        man = json.loads(_read(mpath) or b"")
    except ValueError:
        _bad(report, root, mpath, "elastic.manifest", "deserialize", repair)
        return
    want = man.get("digest") if isinstance(man, dict) else None
    if want is None:
        return  # pre-digest manifest: nothing to verify offline
    body = {k: v for k, v in man.items() if k != "digest"}
    got = hashlib.sha256(
        json.dumps(body, sort_keys=True).encode()).hexdigest()
    if got != want:
        _bad(report, root, mpath, "elastic.manifest", "digest", repair)


def scan_artifacts(root: str, repair: bool = False) -> dict:
    report = _new_report(root, "artifacts")
    _scan_blob_dir(report, root, "memo", ".npz", _MEMO_SCHEMA, repair)
    _scan_blob_dir(report, root, "plancert", ".json", _CERT_SCHEMA, repair)
    _scan_handoffs(report, root, repair)
    return report


def scan_cache(root: str, repair: bool = False) -> dict:
    report = _new_report(root, "cache")
    _scan_blob_dir(report, root, "aot", ".aot", _AOT_SCHEMA, repair)
    _scan_blob_dir(report, root, "programs", ".pkl", _PROGRAM_SCHEMA,
                   repair)
    return report


def scan_checkpoint(path: str, repair: bool = False) -> dict:
    """One checkpoint tree: a direct ``<path>.digests.json`` sidecar, or
    a root holding ``step_<n>/`` layouts (elastic CheckpointManager) —
    every sidecar and MANIFEST self-digest under it."""
    root = os.path.abspath(path)
    report = _new_report(root, "checkpoint")
    side = root + _DIGESTS_SUFFIX
    if os.path.exists(side):
        _scan_sidecar(report, os.path.dirname(root) or root, side, repair)
    for r, _dirs, names in os.walk(root):
        for name in sorted(names):
            full = os.path.join(r, name)
            if name.endswith(_DIGESTS_SUFFIX):
                _scan_sidecar(report, root, full, repair)
            elif name == "MANIFEST.json":
                _scan_manifest_selfdigest(report, root, full, repair)
    return report


def _new_report(root: str, kind: str) -> dict:
    return {"kind": kind, "root": root, "scanned": 0, "corrupt": [],
            "quarantined": [], "repair_errors": []}


def scan(artifacts: Optional[str] = None, cache: Optional[str] = None,
         checkpoints: Optional[List[str]] = None,
         repair: bool = False) -> dict:
    """Importable entry point (bench.py times it; tests drive it).
    Returns ``{"legs": [...], "scanned": n, "corrupt": n, "status": s}``
    with ``status`` matching the CLI exit code."""
    legs = []
    if artifacts and os.path.isdir(artifacts):
        legs.append(scan_artifacts(artifacts, repair))
    if cache and os.path.isdir(cache):
        legs.append(scan_cache(cache, repair))
    for c in checkpoints or []:
        if os.path.exists(c) or os.path.exists(c + _DIGESTS_SUFFIX):
            legs.append(scan_checkpoint(c, repair))
    scanned = sum(leg["scanned"] for leg in legs)
    corrupt = sum(len(leg["corrupt"]) for leg in legs)
    status = EXIT_EMPTY if scanned == 0 else (
        EXIT_CORRUPT if corrupt else EXIT_CLEAN)
    return {"legs": legs, "scanned": scanned, "corrupt": corrupt,
            "status": status}


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="ramba-fsck",
        description="offline integrity verification of ramba_tpu's "
                    "persisted artifacts, caches and checkpoints")
    ap.add_argument("--artifacts", default=os.environ.get("RAMBA_ARTIFACTS"),
                    help="shared artifact tier dir (default: "
                         "RAMBA_ARTIFACTS)")
    ap.add_argument("--cache", default=os.environ.get("RAMBA_CACHE"),
                    help="persistent executable cache dir (default: "
                         "RAMBA_CACHE)")
    ap.add_argument("--checkpoint", action="append", default=[],
                    metavar="PATH",
                    help="checkpoint path or elastic root (repeatable)")
    ap.add_argument("--repair", action="store_true",
                    help="move corrupt entries into quarantine/ beside "
                         "their scan root")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full report as JSON on stdout")
    args = ap.parse_args(argv)

    result = scan(artifacts=args.artifacts, cache=args.cache,
                  checkpoints=args.checkpoint, repair=args.repair)
    if args.as_json:
        json.dump(result, sys.stdout, indent=1, sort_keys=True)
        sys.stdout.write("\n")
    else:
        for leg in result["legs"]:
            print(f"ramba-fsck: {leg['kind']} {leg['root']}: "
                  f"{leg['scanned']} scanned, "
                  f"{len(leg['corrupt'])} corrupt, "
                  f"{len(leg['quarantined'])} quarantined")
            for c in leg["corrupt"]:
                print(f"  CORRUPT {c['path']} [{c['schema']}] "
                      f"{c['reason']}")
        if not result["legs"]:
            print("ramba-fsck: nothing to scan (set RAMBA_ARTIFACTS / "
                  "RAMBA_CACHE or pass --checkpoint)", file=sys.stderr)
    if result["status"] == EXIT_CORRUPT and args.repair and all(
            not leg["repair_errors"] and
            len(leg["quarantined"]) >= len(leg["corrupt"])
            for leg in result["legs"]):
        print("ramba-fsck: corrupt entries quarantined; rerun to verify")
    return result["status"]


if __name__ == "__main__":
    raise SystemExit(main())
