#!/usr/bin/env bash
# Static-quality gate: ramba-lint over a smoke trace, plus ruff + mypy
# when they are installed (CI images have them; minimal containers may
# not — the gate degrades to the parts that exist rather than failing).
#
#   scripts/lint.sh [trace.jsonl ...]
#
# With no arguments, a tiny smoke workload is traced into a tempdir and
# linted strictly (including the --memo-audit replay); passing trace
# paths lints those instead.
set -euo pipefail
cd "$(dirname "$0")/.."

rc=0

if [ "$#" -gt 0 ]; then
    traces=("$@")
else
    td="$(mktemp -d)"
    trap 'rm -rf "$td"' EXIT
    echo "== lint.sh: capturing smoke trace =="
    JAX_PLATFORMS=cpu RAMBA_TRACE="$td/smoke.jsonl" RAMBA_VERIFY=warn \
        RAMBA_MEMO=1 RAMBA_PLANCERT=1 python - <<'EOF'
import numpy as np
import ramba_tpu as rt

a = rt.fromarray(np.arange(64.0).reshape(8, 8))
b = rt.fromarray(np.ones((8, 8)))
for _ in range(3):
    np.asarray((a + b) * 2.0)
np.asarray((a - b).sum())
EOF
    traces=("$td/smoke.jsonl")
fi

echo "== lint.sh: ramba-lint --strict =="
JAX_PLATFORMS=cpu python -m ramba_tpu.analyze --strict "${traces[@]}" || rc=1

echo "== lint.sh: ramba-lint --memo-audit =="
JAX_PLATFORMS=cpu python -m ramba_tpu.analyze --memo-audit "${traces[@]}" || rc=1

echo "== lint.sh: ramba-lint --plan-audit =="
JAX_PLATFORMS=cpu python -m ramba_tpu.analyze --plan-audit "${traces[@]}" || rc=1

echo "== lint.sh: ramba-fsck smoke (seed, verify, flip, repair) =="
ftd="$(mktemp -d)"
if JAX_PLATFORMS=cpu RAMBA_ARTIFACTS="$ftd" python - <<'EOF'
import os
import sys

import numpy as np

from ramba_tpu.fleet import artifacts

sys.path.insert(0, os.path.join(os.getcwd(), "scripts"))
import ramba_fsck  # noqa: E402

artifacts.configure()
assert artifacts.memo_store("fscksmoke0" * 3 + "ab", [np.arange(16.0)])
assert artifacts.memo_store("fscksmoke1" * 3 + "cd", [np.ones(4)])
root = os.environ["RAMBA_ARTIFACTS"]

r = ramba_fsck.scan(artifacts=root)
assert r["status"] == 0 and r["scanned"] >= 2, r

blob = os.path.join(root, "memo", sorted(os.listdir(os.path.join(root, "memo")))[0])
b = bytearray(open(blob, "rb").read())
b[len(b) // 2] ^= 0xFF
open(blob, "wb").write(bytes(b))

r = ramba_fsck.scan(artifacts=root)
assert r["status"] == 1 and r["corrupt"] == 1, r

r = ramba_fsck.scan(artifacts=root, repair=True)
assert r["status"] == 1 and os.path.isdir(os.path.join(root, "quarantine")), r

r = ramba_fsck.scan(artifacts=root)
assert r["status"] == 0, r
print("fsck smoke: detect + quarantine + clean rescan OK")
EOF
then
    :
else
    echo "lint.sh: ramba-fsck smoke FAILED"
    rc=1
fi
rm -rf "$ftd"

if command -v ruff >/dev/null 2>&1; then
    echo "== lint.sh: ruff =="
    ruff check ramba_tpu tests scripts bench.py || rc=1
else
    echo "== lint.sh: ruff not installed, skipping =="
fi

if command -v mypy >/dev/null 2>&1; then
    echo "== lint.sh: mypy (typed-surface gate) =="
    mypy ramba_tpu/analyze ramba_tpu/core/expr.py ramba_tpu/core/memo.py \
        ramba_tpu/core/plancache.py || rc=1
else
    echo "== lint.sh: mypy not installed, skipping =="
fi

exit "$rc"
