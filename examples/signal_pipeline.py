"""Signal-processing pipeline on the linalg/fft namespaces.

A drop-in NumPy workflow — low-pass filter a noisy signal with the fft
family, then least-squares fit the recovered waveform — where every
device-lowerable step fuses into the surrounding flush and runs sharded.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import ramba_tpu as rt

n = 1 << 14
t = np.linspace(0.0, 1.0, n, endpoint=False)
rng = np.random.RandomState(0)
clean = np.sin(2 * np.pi * 5 * t) + 0.5 * np.sin(2 * np.pi * 12 * t)
noisy = rt.fromarray(clean + 0.8 * rng.randn(n))

# low-pass: zero every frequency bin above 20 Hz (on device, fused)
spectrum = rt.fft.rfft(noisy)
freqs = rt.fft.rfftfreq(n, d=t[1] - t[0])
filtered = rt.fft.irfft(rt.where(freqs <= 20.0, spectrum, 0.0))

clean_d = rt.fromarray(clean)
err_before = float(rt.mean(rt.abs(noisy - clean_d)))
err_after = float(rt.mean(rt.abs(filtered - clean_d)))
print(f"mean abs error: {err_before:.3f} -> {err_after:.3f}")

# recover the two component amplitudes by least squares on the design
# matrix [sin 5t, sin 12t]
design = rt.stack(
    [rt.fromarray(np.sin(2 * np.pi * 5 * t)),
     rt.fromarray(np.sin(2 * np.pi * 12 * t))], ).T
coef, *_ = rt.linalg.lstsq(design, filtered)
print("fitted amplitudes:", np.round(np.asarray(coef), 3), "(true: [1.0 0.5])")
