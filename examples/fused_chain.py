"""The reference's headline demo (reference: sample/test-ramba.py): a fused
elementwise chain over a large array.  ``import ramba_tpu as np`` is the
drop-in usage mode; every op below is collected lazily and compiled into a
single XLA kernel per iteration.

Run on a TPU host:  python examples/fused_chain.py
Run on CPU (8 fake devices):
  PYTHONPATH= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/fused_chain.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import time

import ramba_tpu as np

np.sync()
t0 = time.time()
A = np.arange(100 * 1000 * 1000) / 1000.0
np.sync()
print("Initialize array time:", time.time() - t0)

for i in range(5):
    t0 = time.time()
    B = np.sin(A)
    C = np.cos(A)
    D = B * B + C ** 2
    np.sync()
    print("Iteration", i + 1, "time:", time.time() - t0)

print("checksum (== num elements):", float(np.sum(D)))
