"""Tour of the algorithmic skeletons (reference: docs/index.md:83-267):
smap / sreduce / scumulative / spmd / groupby, all running over sharded
arrays on the device mesh.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

import ramba_tpu as rt

n = 1_000_000

# smap: elementwise kernel written against NumPy, fused into the lazy graph
a = rt.arange(n)
b = rt.smap(lambda x: np.sqrt(x) + 1.0, a)
print("smap:", float(b[10]))

# smap_index: kernel sees the global index tuple
c = rt.smap_index(lambda idx, x: x * (idx[0] % 2), a)
print("smap_index:", float(rt.sum(c)))

# sreduce with a worker/driver reducer split (tree reduction over shards)
red = rt.SreduceReducer(lambda x, y: x + y, lambda x, y: x + y)
total = rt.sreduce(lambda x: x * 2.0, red, 0.0, rt.arange(1000.0))
print("sreduce:", float(total))

# scumulative: parallel block scans + carry chain
run_max = rt.scumulative(lambda x, c: np.maximum(x, c),
                         lambda c, block: np.maximum(block, c),
                         rt.fromarray(np.random.RandomState(0).rand(10000)))
print("scumulative (running max tail):", float(run_max[-1]))

# spmd: explicit per-worker kernels over local shards
def double_local(v):
    v.set_local(v.get_local() * 2.0)

x = rt.arange(1024.0)
rt.spmd(double_local, x)
print("spmd:", float(x[3]))

# groupby: segment reductions + group-broadcast ops (climatology/anomaly)
days = np.arange(365) % 7
temps = rt.fromarray(np.random.RandomState(1).rand(8, 365))
gb = temps.groupby(1, days, num_groups=7)
anomaly = gb - gb.mean()
print("groupby anomaly shape:", anomaly.shape)
