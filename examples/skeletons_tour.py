"""Tour of the algorithmic skeletons (reference: docs/index.md:83-267):
smap / sreduce / scumulative / spmd / groupby, all running over sharded
arrays on the device mesh.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import numpy as np

import ramba_tpu as rt

n = 1_000_000

# smap: elementwise kernel written against NumPy, fused into the lazy graph
a = rt.arange(n)
b = rt.smap(lambda x: np.sqrt(x) + 1.0, a)
print("smap:", float(b[10]))

# smap_index: kernel sees the global index tuple
c = rt.smap_index(lambda idx, x: x * (idx[0] % 2), a)
print("smap_index:", float(rt.sum(c)))

# sreduce with a worker/driver reducer split (tree reduction over shards)
red = rt.SreduceReducer(lambda x, y: x + y, lambda x, y: x + y)
total = rt.sreduce(lambda x: x * 2.0, red, 0.0, rt.arange(1000.0))
print("sreduce:", float(total))

# scumulative: parallel block scans + carry chain
run_max = rt.scumulative(lambda x, c: np.maximum(x, c),
                         lambda c, block: np.maximum(block, c),
                         rt.fromarray(np.random.RandomState(0).rand(10000)))
print("scumulative (running max tail):", float(run_max[-1]))

# spmd: explicit per-worker kernels over local shards
def double_local(v):
    v.set_local(v.get_local() * 2.0)

x = rt.arange(1024.0)
rt.spmd(double_local, x)
print("spmd:", float(x[3]))

# groupby: segment reductions + group-broadcast ops (climatology/anomaly)
days = np.arange(365) % 7
temps = rt.fromarray(np.random.RandomState(1).rand(8, 365))
gb = temps.groupby(1, days, num_groups=7)
anomaly = gb - gb.mean()
print("groupby anomaly shape:", anomaly.shape)

# LocalView.halo: neighbor shard access inside an spmd kernel (the
# reference's getborder surface) — here a 3-point smoothing sweep
src = rt.fromarray(np.arange(4096.0))
dst = rt.zeros(4096)
rt.sync()

def smooth(s, d):
    h = s.halo(1)                      # block + 1 neighbor cell each side
    d.set_local((h[:-2] + h[1:-1] + h[2:]) / 3.0)

rt.spmd(smooth, src, dst)
print("spmd halo smooth:", float(dst[2048]))

# sstencil_iterate: many sweeps in ONE compiled on-device loop — the
# device-resident replacement for per-sweep dispatch
@rt.stencil
def jacobi(a):
    return 0.25 * (a[-1, 0] + a[1, 0] + a[0, -1] + a[0, 1])

grid = rt.fromarray(np.random.RandomState(2).rand(256, 256))
relaxed = rt.sstencil_iterate(jacobi, grid, 100)   # 100 sweeps, one program
print("sstencil_iterate(100):", float(rt.mean(relaxed)))
