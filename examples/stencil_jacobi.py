"""Distributed stencils: PRK-style star stencil and a Jacobi sweep.

Reference: the stencil skeleton (docs/index.md "Stencils"; the PRK star
benchmark README.md:271-299).  On TPU the halo exchange the reference does
with point-to-point border messages is a GSPMD collective-permute over ICI,
and single-chip runs use a hand-tiled Pallas kernel.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


import time

import numpy as np

import ramba_tpu as rt


@rt.stencil
def star2(a):
    return (
        0.25 * (a[0, 1] + a[0, -1] + a[1, 0] + a[-1, 0])
        + 0.125 * (a[0, 2] + a[0, -2] + a[2, 0] + a[-2, 0])
    )


@rt.stencil
def jacobi(a):
    return 0.25 * (a[0, 1] + a[0, -1] + a[1, 0] + a[-1, 0])


n = 4096
x = rt.fromarray(np.random.RandomState(0).rand(n, n).astype(np.float32))
rt.sync()

for name, kern, iters in [("star r=2", star2, 10), ("jacobi", jacobi, 10)]:
    y = x
    t0 = time.time()
    for _ in range(iters):
        y = rt.sstencil(kern, y)
    s = float(rt.sum(y))  # completion barrier
    dt = time.time() - t0
    mflops = 13 * (n - 4) ** 2 * iters / dt / 1e6 if name.startswith("star") else 0
    print(f"{name}: {iters} iters in {dt:.3f}s"
          + (f"  ({mflops:.0f} PRK-MFlops)" if mflops else ""))
