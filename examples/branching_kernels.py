"""Data-dependent branches in skeleton kernels, auto-lowered to the device.

The reference Numba-compiles arbitrary Python kernels, branches included
(/root/reference/ramba/ramba.py:1600-1694).  On TPU, XLA cannot compile
`if x > 0:` on traced data — so the framework re-executes the kernel once
per reachable branch path (a two-sided trace) and combines the results
with `jnp.where` on the recorded conditions: the reference's per-element
branch semantics, at XLA speed, no host fallback.

Run on CPU (8 fake devices):
  PYTHONPATH= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/branching_kernels.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import ramba_tpu as rt


def main():
    n = 1_000_000
    x = rt.fromarray(np.linspace(-3.0, 3.0, n))

    # a piecewise activation written as plain Python — three branch paths
    def leaky_clip(v):
        if v > 1.0:
            return 1.0 + 0.01 * (v - 1.0)
        elif v < -1.0:
            return -1.0 + 0.01 * (v + 1.0)
        return v

    y = rt.smap(leaky_clip, x)

    # a branching reducer: keep the max unless it is negative
    best = rt.sreduce(
        lambda v: v,
        lambda a, b: a if a > b else b,
        -np.inf,
        y,
    )

    # a branching stencil body: per-point upwind selection
    @rt.stencil
    def upwind(a):
        v = a[0, 1] - a[0, -1]
        if v > 0:
            return a[0, 0] - a[0, -1]
        return a[0, 1] - a[0, 0]

    g = rt.fromarray(np.random.RandomState(0).rand(512, 512).astype(np.float32))
    flux = rt.sstencil(upwind, g)

    print("smap  branch kernel:", np.asarray(y[:3]).round(3))
    print("sreduce branch max :", float(best))
    print("stencil branch sum :", float(rt.sum(flux)))


if __name__ == "__main__":
    main()
